"""Serving engine: continuous batching over a paged KV pool.

The data plane the control plane orchestrates — the SGLang-on-JAX-equivalent
(the reference deploys SGLang in its role pods; BASELINE.md configs). One
Engine = one model replica on one JAX program (single chip or a whole slice
via the tp/sp mesh).

Design (TPU-first):
* **Bucketed static shapes** — one compiled program per (batch, chunk)
  bucket; prefill chunks and decode steps reuse the same ``forward_paged``.
* **Host-side logistics, device-side math** — page tables/lengths are plain
  numpy handed to jit as arrays; the graph never sees Python branching.
* **Chunked prefill** — long prompts stream through a fixed-size chunk
  program, so TTFT for short prompts never waits behind a long compile.
* **Radix prefix cache** — page-granular prefix sharing with LRU eviction.
* **Preemption** — page exhaustion preempts the youngest request back to the
  waiting queue (its pages recycle; the radix cache softens the re-prefill).

Modes: ``unified`` (prefill+decode co-located), ``prefill`` (produces KV
pages + first token for a peer), ``decode`` (imports KV pages) — see
rbg_tpu.engine.pd for the disaggregated pair.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import json
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rbg_tpu.engine.config import EngineConfig, SamplingParams
from rbg_tpu.engine.kvcache import PageAllocator, PagedKVCache, pages_for_tokens
from rbg_tpu.engine.radix_cache import RadixCache
from rbg_tpu.engine.sampler import NEG_INF, row_keys, sample, step_keys
from rbg_tpu.obs.names import (PROGRAM_FUSED_DECODE, PROGRAM_PAGED_FWD,
                               PROGRAM_RAGGED_FWD, PROGRAM_SAMPLER,
                               PROGRAM_SPEC_VERIFY)
from rbg_tpu.models.llama import forward_paged, forward_ragged, init_params
from rbg_tpu.obs import names as obs_names
from rbg_tpu.obs.metrics import REGISTRY


@dataclasses.dataclass
class StepEvent:
    request_id: int
    token: int
    finished: bool
    text_done: bool = False
    logprob: Optional[float] = None


class Request:
    _ids = itertools.count()

    def __init__(self, prompt: List[int], sampling: SamplingParams):
        self.id = next(Request._ids)
        self.prompt = list(prompt)
        # _preempt folds generated output into prompt for re-prefill;
        # everything past this index is OUTPUT for penalty accounting
        # (presence/frequency act on generated tokens only).
        self.orig_prompt_len = len(prompt)
        self.sampling = sampling
        self.output: List[int] = []
        self.state = "waiting"          # waiting | prefill | running | finished
        self.pages: List[int] = []
        self.shared_tokens = 0          # radix-matched prefix (page-aligned)
        self.prefill_pos = 0            # next prompt index to prefill
        self.seq_len = 0                # tokens materialized in KV
        self.last_token: Optional[int] = None
        self.ngram = None                   # NGramIndex, speculative mode
        self.gstate = None                  # grammar state (json_mode/regex)
        self.grammar = None                 # this request's TokenGrammar
        self.lora_idx = 0                   # adapter slot (0 = base model)
        self.t_submit = time.perf_counter()
        self.t_first: Optional[float] = None
        # Continuous-admission accounting: the engine step at which the
        # request entered `waiting`, and how many admission attempts it sat
        # out because capacity (a batch slot or KV pages) was unavailable.
        # The difference (wait − blocked) is the request's EXCESS wait —
        # steps it queued beyond what resource availability forced — and
        # the continuous-batching invariant bounds it at one step.
        self.enqueue_step = 0
        self.blocked_steps = 0
        # Wall-clock twin of enqueue_step: when the request last entered
        # `waiting` (submit or preemption) — the join-latency metric
        # measures from here, not t_submit, so a preempted-then-readmitted
        # request's running time never reads as queue wait.
        self.t_enqueue = self.t_submit

    @property
    def total_len(self) -> int:
        return len(self.prompt) + len(self.output)

    def max_len(self) -> int:
        return len(self.prompt) + self.sampling.max_new_tokens


class Engine:
    def __init__(self, cfg: EngineConfig, params: Optional[dict] = None,
                 mesh=None):
        cfg.validate()
        self.cfg = cfg
        self.mcfg = cfg.model_config
        self.mesh = mesh
        key = jax.random.key(cfg.seed)
        if params is not None:
            self.params = params
        elif cfg.checkpoint_path:
            from rbg_tpu.models.checkpoint import load_params
            self.params = load_params(cfg.checkpoint_path, self.mcfg)
        else:
            self.params = init_params(self.mcfg, key)
        # Base for per-row sampling streams: a request's randomness is
        # fold_in(row_key, position) — row_key from its seed (reproducible)
        # or from this base + request id (distinct streams). See sampler.py.
        self._sample_base = jax.random.key(cfg.seed + 1)

        self.cache = PagedKVCache.create(self.mcfg, cfg.num_pages, cfg.page_size,
                                         quantize=(cfg.kv_dtype == "int8"))
        self.allocator = PageAllocator(cfg.num_pages)
        self.radix = RadixCache(self.allocator, cfg.page_size) if cfg.enable_radix_cache else None
        # Host-DRAM spill tier under the device pool (engine/kvtier.py):
        # radix evictions spill into it, admission promotes out of it.
        self.host_tier = None
        if cfg.host_tier_bytes and self.radix is not None \
                and not self.cache.quantized:
            from rbg_tpu.engine.kvtier import HostKVTier
            self.host_tier = HostKVTier(cfg.page_size, cfg.host_tier_bytes)

        if mesh is not None:
            self._shard_state(mesh)

        self.waiting: List[Request] = []
        self.running: List[Request] = []
        self.requests: Dict[int, Request] = {}
        self._fwd_cache: Dict[Tuple[int, int], object] = {}
        self._samplers: Dict[Tuple[bool, bool], object] = {}
        # Fused decode path: device-resident (tok, pos, kvl, table, …) state
        # plus a one-step emission lag so host bookkeeping for step N+1
        # overlaps the device computing step N (see _decode_step).
        self._dec: Optional[dict] = None
        self._dec_fn_cache: Dict[Tuple[int, bool, bool], object] = {}
        self._spec_fn_cache: Dict[Tuple[int, bool, bool, bool, bool], object] = {}
        # Ragged unified prefill/decode dispatch: one compiled program per
        # (row bucket, packed-token bucket).
        self._ragged_fn_cache: Dict[Tuple[int, int], object] = {}
        # Set by the serving loop when submissions are waiting beyond this
        # step's admissions — the fused decode scan shortens its window so
        # the join is absorbed next step instead of a full multi_step
        # window later. Loop-thread-confined (single-writer, like all
        # engine state); cleared at the end of every step.
        self.join_hint = False
        # Seconds each admitted request waited between entering the engine
        # queue and joining the running batch — drained by the service
        # loop into rbg_serving_join_latency_seconds.
        self.last_join_waits: List[float] = []
        self.grammar = None     # TokenGrammar — enable_json_grammar()
        self._token_bytes = None
        self._grammar_eos = None
        self._token_trie = None
        self._regex_grammars = collections.OrderedDict()
        # Device-resident grammar tables: one upload per batch grammar
        # combination (per-grammar np tables cached on the TokenGrammar
        # itself; see _device_grammar_tables).
        self._gtable_dev = collections.OrderedDict()
        # Events drained outside step() (e.g. a runtime load_lora must
        # flush the fused pipeline) surface on the NEXT step() call.
        self._deferred_events: List[StepEvent] = []
        # Multi-LoRA: name → slot (0 = reserved no-adapter slot); stacked
        # arrays rebuilt on load (rank-padded so one program serves all).
        self._lora_slots: Dict[str, int] = {}
        self._lora_raw: List[Tuple[dict, float]] = []
        self.lora_stack: Optional[dict] = None
        self.metrics = {"steps": 0, "decode_tokens": 0, "prefill_tokens": 0,
                        "radix_hit_tokens": 0, "host_hit_tokens": 0,
                        "preemptions": 0,
                        "spec_drafted": 0, "spec_accepted": 0,
                        "spec_steps": 0, "unified_steps": 0, "joins": 0,
                        "join_wait_steps_max": 0, "join_excess_steps_max": 0}

    def _shard_state(self, mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from rbg_tpu.parallel.sharding import param_specs, shard_pytree
        self.params = shard_pytree(
            self.params, param_specs(self.mcfg, self.params), mesh)
        # GQA pages shard over tp on the KV-head axis; the MLA latent pool
        # has no head axis and replicates (it is ~10x smaller).
        page_spec = NamedSharding(
            mesh, P() if self.mcfg.mla else P(None, None, None, "tp", None))
        self.cache = PagedKVCache(
            k_pages=jax.device_put(self.cache.k_pages, page_spec),
            v_pages=jax.device_put(self.cache.v_pages, page_spec),
            k_scales=(jax.device_put(self.cache.k_scales, page_spec)
                      if self.cache.quantized else None),
            v_scales=(jax.device_put(self.cache.v_scales, page_spec)
                      if self.cache.quantized else None),
        )

    # ---- public API ----

    def _check_prompt(self, prompt: List[int]) -> None:
        """Reject wire-supplied token ids outside the vocab — they would
        crash the single engine loop thread later (penalty mask indexing,
        embedding gather on some backends) instead of failing one request."""
        V = self.mcfg.vocab_size
        if not prompt:
            raise ValueError("empty prompt")
        lo, hi = min(prompt), max(prompt)   # C-speed; this runs per admission
        if lo < 0 or hi >= V:
            bad = lo if lo < 0 else hi
            raise ValueError(
                f"prompt token {bad} outside model vocab [0, {V})")

    def enable_json_grammar(self, tokenizer) -> None:
        """Wire grammar-constrained decoding (json_mode AND regex
        requests) to a tokenizer's token→bytes table. Callers that admit
        constrained requests without this get a per-request admission
        error."""
        from rbg_tpu.engine.grammar import (JsonGrammar, TokenGrammar,
                                            TokenTrie, token_bytes_for)
        self._token_bytes = token_bytes_for(tokenizer)
        self._grammar_eos = tokenizer.eos_id
        # ONE trie per tokenizer, shared by the JSON grammar and every
        # cached regex grammar (it depends only on the vocab).
        self._token_trie = TokenTrie(self._token_bytes)
        self.grammar = TokenGrammar(JsonGrammar(), self._token_bytes,
                                    self._grammar_eos,
                                    trie=self._token_trie)
        self._regex_grammars = collections.OrderedDict()
        self._gtable_dev = collections.OrderedDict()

    _REGEX_GRAMMAR_CACHE = 64

    def _regex_grammar(self, pattern: str):
        return self._compiled_grammar(("re", pattern))

    def _compiled_grammar(self, key, schema: Optional[dict] = None):
        """Per-pattern/per-schema compiled TokenGrammar (NFA + shared
        trie + mask cache), LRU-bounded — repeat constraints (the common
        case: one schema per client) pay compilation once. Raises
        ValueError on bad inputs (an admission error, never a loop
        failure)."""
        from rbg_tpu.engine.grammar import (JsonSchemaGrammar, RegexGrammar,
                                            TokenGrammar)
        tg = self._regex_grammars.get(key)
        if tg is not None:
            self._regex_grammars.move_to_end(key)  # LRU refresh
            return tg
        byte_grammar = (RegexGrammar(key[1]) if key[0] == "re"
                        else JsonSchemaGrammar(schema))
        tg = TokenGrammar(byte_grammar, self._token_bytes,
                          self._grammar_eos, trie=self._token_trie)
        if len(self._regex_grammars) >= self._REGEX_GRAMMAR_CACHE:
            self._regex_grammars.popitem(last=False)
        self._regex_grammars[key] = tg
        return tg

    def _grammar_for(self, sampling: SamplingParams):
        if sampling.json_mode:
            return self.grammar
        if sampling.regex is not None:
            return self._compiled_grammar(("re", sampling.regex))
        if sampling.json_schema is not None:
            if not sampling.json_schema:
                return self.grammar   # {} = "any JSON" (vLLM semantics)
            # Key preserves property ORDER (no sort_keys): compilation is
            # order-sensitive — properties emit in declaration order, so
            # order-differing schemas must not share a grammar.
            key = ("schema", json.dumps(sampling.json_schema))
            return self._compiled_grammar(key, sampling.json_schema)
        return None

    _LORA_ATTN_TARGETS = ("wq", "wk", "wv", "wo")
    _LORA_MLP_TARGETS = ("w_gate", "w_up", "w_down")

    def load_lora(self, name: str, adapter: dict, alpha: float = 16.0):
        """Register a LoRA adapter for per-request batched serving.

        ``adapter``: {target: (A [L, d_in, r], B [L, r, d_out])} for any of
        wq/wk/wv/wo (GQA) or wq/w_dkv/wo (MLA), plus w_gate/w_up/w_down on
        dense-MLP models. All loaded
        adapters are stacked (rank-padded, alpha/r folded into B
        per-target) into one [L, n, ...] array set so a single compiled
        program serves every batch mix — per-row adapter gather inside the
        jitted step (punica/S-LoRA), no recompile per adapter."""
        if not adapter:
            raise ValueError("empty adapter")
        if name in self._lora_slots:
            raise ValueError(f"adapter {name!r} already loaded")
        if self.mcfg.mla:
            # MLA: LoRA targets the PLAIN input projections + output;
            # the absorbed per-head up-projections (w_uk/w_uv) are not
            # adapter targets.
            allowed = {"wq", "w_dkv", "wo"}
        else:
            allowed = set(self._LORA_ATTN_TARGETS)
        if self.mcfg.num_experts == 0:
            allowed |= set(self._LORA_MLP_TARGETS)
        L = self.mcfg.num_layers
        base = self.params["blocks"]
        for tgt, (A, B) in adapter.items():
            if tgt not in allowed:
                # A typo'd/unsupported target would be a silent no-op —
                # _lora_proj matches exact names on the dense paths only.
                raise ValueError(
                    f"adapter {name!r}: unsupported target {tgt!r} "
                    f"(supported here: {sorted(allowed)})")
            if A.shape[0] != L or B.shape[0] != L or A.shape[2] != B.shape[1]:
                raise ValueError(
                    f"adapter {name!r} target {tgt!r}: bad shapes "
                    f"{A.shape} / {B.shape}")
            bw = base[tgt]
            if A.shape[1] != bw.shape[1] or B.shape[2] != bw.shape[2]:
                raise ValueError(
                    f"adapter {name!r} target {tgt!r}: dims {A.shape[1]}→"
                    f"{B.shape[2]} do not match base weight "
                    f"{bw.shape[1]}→{bw.shape[2]} (wrong base model?)")
        # Commit only after a successful rebuild — a half-registered slot
        # would resolve past the stack and JAX's clamped gather would
        # silently serve a DIFFERENT adapter.
        self._lora_raw.append((adapter, float(alpha)))
        try:
            self._rebuild_lora_stack()
        except Exception:
            self._lora_raw.pop()
            raise
        self._lora_slots[name] = len(self._lora_raw)

    def _rebuild_lora_stack(self):
        L = self.mcfg.num_layers
        n = len(self._lora_raw) + 1                     # + no-adapter slot 0
        targets = sorted({t for ad, _ in self._lora_raw for t in ad})
        rmax = max(A.shape[2] for ad, _ in self._lora_raw
                   for A, _B in ad.values())
        stack = {}
        dt = self.mcfg.jax_dtype
        for tgt in targets:
            d_in = next(A.shape[1] for ad, _ in self._lora_raw
                        if tgt in ad for A, _B in [ad[tgt]])
            d_out = next(B.shape[2] for ad, _ in self._lora_raw
                         if tgt in ad for _A, B in [ad[tgt]])
            As = np.zeros((L, n, d_in, rmax), np.float32)
            Bs = np.zeros((L, n, rmax, d_out), np.float32)
            for i, (ad, alpha) in enumerate(self._lora_raw):
                if tgt in ad:
                    A, B = ad[tgt]
                    r = A.shape[2]
                    As[:, i + 1, :, :r] = np.asarray(A, np.float32)
                    # Per-TARGET scaling: alpha/r with THIS target's rank
                    # (mixed-rank adapters would otherwise mis-scale).
                    Bs[:, i + 1, :r, :] = (np.asarray(B, np.float32)
                                           * (alpha / r))
            stack[tgt] = (jnp.asarray(As, dt), jnp.asarray(Bs, dt))
        self.lora_stack = stack
        # The compiled variants bind the stack shape — new adapters mean
        # new shapes, so old cached programs are stale. DRAIN the fused
        # pipeline first: discarding self._dec would lose the pending
        # window's tokens while seq_len already counts them (corrupting
        # every in-flight request on a runtime load).
        self._deferred_events.extend(self._drain_decode())
        self._fwd_cache.clear()
        self._dec_fn_cache.clear()
        self._spec_fn_cache.clear()

    def _resolve_lora(self, sampling: SamplingParams) -> int:
        if sampling.lora is None:
            return 0
        slot = self._lora_slots.get(sampling.lora)
        if slot is None:
            raise ValueError(
                f"unknown LoRA adapter {sampling.lora!r}; loaded: "
                f"{sorted(self._lora_slots) or 'none'}")
        return slot

    def _grammar_check(self, sampling: SamplingParams) -> None:
        constrained = (sampling.json_mode or sampling.regex is not None
                       or sampling.json_schema is not None)
        if constrained and self.grammar is None:
            raise ValueError(
                "json_mode/regex/json_schema require a grammar table — the "
                "server wires it from the tokenizer (enable_json_grammar)")
        if constrained:
            # Bad pattern/schema → admission error, never a loop failure.
            self._grammar_for(sampling)

    def _gmask(self, grammar, state) -> np.ndarray:
        """Grammar mask padded to MODEL vocab: ids beyond the tokenizer's
        vocab can never be legal constrained output."""
        V = self.mcfg.vocab_size
        m = grammar.mask(state)
        if len(m) == V:
            return m
        out = np.zeros(V, bool)
        out[:min(len(m), V)] = m[:V]
        return out

    # ---- device-resident grammar tables ----

    # Multi-grammar combination LRU: shallow on purpose — each entry
    # duplicates its grammars' device blocks, so hold only the current
    # composition plus one predecessor (ping-pong recompositions).
    _GTABLE_DEV_CACHE = 2

    def _grammar_table(self, tg):
        """The host-side GrammarTable for a TokenGrammar, or None when the
        grammar must stay on the host-synced path (tables disabled,
        pushdown JSON grammar, or state budget exceeded). The compile —
        and a budget failure — is cached on the grammar object, which is
        itself LRU-cached per pattern/schema, so each grammar pays BFS
        once per engine lifetime."""
        if self.cfg.grammar_table == "off":
            return None
        from rbg_tpu.engine.grammar import NfaGrammar, compile_token_table
        if not isinstance(tg.grammar, NfaGrammar):
            return None     # JsonGrammar: pushdown, no finite token table
        budget = self.cfg.grammar_state_budget
        cached = getattr(tg, "_table_cache", None)
        if cached is not None and cached[0] == budget:
            return cached[1]
        table = compile_token_table(tg, budget, self.mcfg.vocab_size)
        tg._table_cache = (budget, table)
        return table

    def _row_fusable(self, r: Request) -> bool:
        """True when the row can decode inside the fused scan: no grammar,
        or a grammar with a compiled device table."""
        return r.grammar is None or self._grammar_table(r.grammar) is not None

    def _grammar_dev_block(self, tg):
        """A grammar's table on device, offset-free, padded to the next
        POWER-OF-TWO state count (rows past the table are -1/False,
        unreachable) — ONE upload per (grammar, vocab), cached on the
        grammar object. Pow-2 buckets keep [S, V] shapes stable across
        similarly-sized grammars (compiled decode programs reuse within a
        bucket, ≤ log2(budget) shapes total) WITHOUT paying a full
        budget-sized block for a 3-state regex: blocks live as long as
        their grammar sits in the pattern/schema LRU, so the aggregate
        device retention is Σ pow2(S_g) × V × 5 bytes over cached
        grammars, not 64 × budget × V × 5."""
        budget = self.cfg.grammar_state_budget
        cached = getattr(tg, "_dev_block", None)
        if cached is not None and cached[0] == budget:
            return cached[1], cached[2]
        t = self._grammar_table(tg)
        V = self.mcfg.vocab_size
        S = 1
        while S < t.num_states:
            S *= 2
        nxt = np.full((S, V), -1, np.int32)
        leg = np.zeros((S, V), bool)
        nxt[:t.num_states] = t.next_state
        leg[:t.num_states] = t.legal
        nxt_dev, leg_dev = jnp.asarray(nxt), jnp.asarray(leg)
        tg._dev_block = (budget, nxt_dev, leg_dev)
        return nxt_dev, leg_dev

    def _device_grammar_tables(self, grammars):
        """(next_state_dev [S, V] int32, legal_dev [S, V] bool, offsets)
        for a batch's grammars: per-grammar device blocks concatenated
        with per-grammar state-id offsets so one array pair serves every
        row (a row's device gstate = offset + its table's local state
        id). The common single-grammar batch reuses the grammar's own
        block directly — no copy; multi-grammar combinations concatenate
        ON DEVICE (offsets applied with a where, no host re-upload) and
        are LRU-cached only shallowly: combinations are transient batch
        compositions, and each held entry duplicates its blocks' memory.
        Entries hold strong grammar refs so the id()-keys stay valid
        while cached."""
        uniq, seen = [], set()
        for g in grammars:
            if id(g) not in seen:
                seen.add(id(g))
                uniq.append(g)
        if len(uniq) == 1:
            nxt, leg = self._grammar_dev_block(uniq[0])
            return nxt, leg, {id(uniq[0]): 0}
        key = tuple(sorted(id(g) for g in uniq))
        hit = self._gtable_dev.get(key)
        if hit is not None:
            self._gtable_dev.move_to_end(key)
            return hit[0], hit[1], hit[2]
        offsets: Dict[int, int] = {}
        nexts, legals = [], []
        off = 0
        for g in uniq:
            nxt, leg = self._grammar_dev_block(g)
            offsets[id(g)] = off
            nexts.append(jnp.where(nxt >= 0, nxt + off, -1))
            legals.append(leg)
            off += nxt.shape[0]
        entry = (jnp.concatenate(nexts), jnp.concatenate(legals),
                 offsets, list(uniq))
        self._gtable_dev[key] = entry
        if len(self._gtable_dev) > self._GTABLE_DEV_CACHE:
            self._gtable_dev.popitem(last=False)
        return entry[0], entry[1], entry[2]

    def add_request(self, prompt: List[int],
                    sampling: Optional[SamplingParams] = None) -> int:
        sampling = sampling or SamplingParams()
        self._check_prompt(prompt)
        self._grammar_check(sampling)
        if len(prompt) + sampling.max_new_tokens > self.cfg.max_seq_len:
            raise ValueError(
                f"prompt+max_new_tokens {len(prompt)}+{sampling.max_new_tokens} "
                f"exceeds max_seq_len {self.cfg.max_seq_len}")
        req = Request(prompt, sampling)
        req.lora_idx = self._resolve_lora(sampling)
        req.enqueue_step = self.metrics["steps"]
        g = self._grammar_for(sampling)
        if g is not None:
            req.grammar = g
            req.gstate = g.initial()
        self.requests[req.id] = req
        self.waiting.append(req)
        return req.id

    def add_request_with_prefix(self, prompt: List[int],
                                sampling: Optional[SamplingParams],
                                prefix_len: int,
                                k_data, v_data) -> Optional[int]:
        """Admit a request whose first ``prefix_len`` tokens' KV arrives
        precomputed (fetched from the shared KV pool — the Mooncake-reuse
        path, keps/74): the pages are written into the local pool and
        prefill resumes at ``prefix_len``. ``prefix_len`` must be
        page-aligned and < len(prompt) (the last token always prefills for
        logits). Returns None when no pages are free (caller falls back to
        a cold prefill through the normal admission queue)."""
        sampling = sampling or SamplingParams()
        self._check_prompt(prompt)
        self._grammar_check(sampling)
        lora_idx = self._resolve_lora(sampling)  # before alloc: no page leak
        ps = self.cfg.page_size
        if prefix_len % ps or not 0 < prefix_len < len(prompt):
            raise ValueError(f"prefix_len {prefix_len} must be page-aligned "
                             f"and in (0, {len(prompt)})")
        if len(prompt) + sampling.max_new_tokens > self.cfg.max_seq_len:
            raise ValueError("prompt+max_new_tokens exceeds max_seq_len")
        need = pages_for_tokens(len(prompt) + 1, ps)
        pages = self._alloc(need)
        if pages is None:
            return None
        n_prefix = prefix_len // ps
        ids = jnp.asarray(pages[:n_prefix], jnp.int32)
        try:
            self.cache = PagedKVCache(
                k_pages=self.cache.k_pages.at[:, ids].set(
                    jnp.asarray(k_data, self.cache.k_pages.dtype)),
                v_pages=self.cache.v_pages.at[:, ids].set(
                    jnp.asarray(v_data, self.cache.v_pages.dtype)),
                k_scales=self.cache.k_scales, v_scales=self.cache.v_scales,
            )
        except (ValueError, TypeError) as e:
            # Foreign pool data (e.g. a replica with different model
            # geometry sharing the pool): the freshly allocated pages must
            # go back or every bad hit leaks them until admission wedges.
            self.allocator.release(pages)
            raise ValueError(f"prefix KV rejected: {e}") from e
        req = Request(prompt, sampling)
        req.lora_idx = lora_idx
        g = self._grammar_for(sampling)
        if g is not None:
            req.grammar = g
            req.gstate = g.initial()
        req.pages = pages
        req.prefill_pos = prefix_len
        req.seq_len = prefix_len
        req.state = "prefill"
        self.requests[req.id] = req
        self.running.append(req)
        self.metrics["pool_hit_tokens"] = (
            self.metrics.get("pool_hit_tokens", 0) + prefix_len)
        return req.id

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    def step(self) -> List[StepEvent]:
        """One scheduler iteration: admit, then either the ragged UNIFIED
        dispatch (prefill chunks + decode steps of the whole batch in one
        program — continuous batching, no phase split; MLA rides it via
        the ragged latent path since round 16) or the legacy split
        prefill→decode paths (pure-decode batches always take the fused
        multi-step scan; cfg.ragged='off', speculative, and LoRA-mixed
        batches keep the split paths throughout)."""
        events: List[StepEvent] = []
        if self._deferred_events:
            events.extend(self._deferred_events)
            self._deferred_events = []
        self.metrics["steps"] += 1
        self._admit()
        if self._unified_eligible():
            self.metrics["unified_steps"] += 1
            events.extend(self._unified_step())
        else:
            events.extend(self._prefill_step())
            events.extend(self._decode_step())
        self.join_hint = False
        return events

    def generate(self, prompts: List[List[int]],
                 sampling: Optional[SamplingParams] = None) -> List[List[int]]:
        ids = [self.add_request(p, sampling) for p in prompts]
        outputs = {i: [] for i in ids}
        while self.has_work():
            for ev in self.step():
                if ev.request_id in outputs:
                    outputs[ev.request_id].append(ev.token)
        return [outputs[i] for i in ids]

    # ---- admission ----

    def _admit(self):
        blocked = False
        while self.waiting:
            if len(self.running) >= self.cfg.max_batch:
                blocked = True   # a batch slot is the unavailable resource
                break
            req = self.waiting[0]
            matched, shared_pages = 0, []
            radix_matched = host_matched = 0
            if (self.radix is not None and req.state == "waiting"
                    and req.lora_idx == 0):
                # Keep at least the prompt's last token for prefill (logits).
                # Adapter requests skip the prefix cache: their KV differs
                # from base-model KV for the same tokens.
                matched, shared_pages = self.radix.match(req.prompt[:-1])
                radix_matched = matched
                if self.host_tier is not None:
                    matched, shared_pages = self._promote_host(
                        req, matched, shared_pages)
                    host_matched = matched - radix_matched
            # Admit with pages for the PROMPT + first token only — decode
            # grows page-by-page (memory oversubscription; preemption
            # reclaims on exhaustion). Reserving max_len up front would
            # forfeit continuous batching's throughput.
            need = (pages_for_tokens(len(req.prompt) + 1, self.cfg.page_size)
                    - len(shared_pages))
            pages = self._alloc(need)
            if pages is None:
                if shared_pages:
                    self.allocator.release(shared_pages)
                blocked = True
                break  # no capacity — stay queued
            self.waiting.pop(0)
            # Join accounting for the continuous-admission invariant: a
            # request admitted at the first step after enqueue waited 0.
            wait = max(0, self.metrics["steps"] - req.enqueue_step - 1)
            excess = max(0, wait - req.blocked_steps)
            self.metrics["joins"] += 1
            self.metrics["join_wait_steps_max"] = max(
                self.metrics["join_wait_steps_max"], wait)
            self.metrics["join_excess_steps_max"] = max(
                self.metrics["join_excess_steps_max"], excess)
            self.last_join_waits.append(time.perf_counter() - req.t_enqueue)
            # Bounded: only the service loop drains this (PD workers and
            # generate() step the engine directly) — cap so an undrained
            # engine never leaks; the loop drains every step, so real
            # serving never comes near the cap.
            del self.last_join_waits[:-1024]
            req.blocked_steps = 0
            req.pages = shared_pages + pages
            req.shared_tokens = matched
            req.prefill_pos = matched
            req.seq_len = matched
            req.state = "prefill"
            self.running.append(req)
            # Hit accounting happens HERE, on admission success, and the
            # two tiers' counters sum to the request's total hit. A
            # promotion whose request then fails its remaining alloc
            # must count NOTHING: the promoted pages entered the radix,
            # so the retry's radix.match re-finds them — charging the
            # promotion too would double-count the same tokens. Same
            # rule for the registry tier counters: a blocked request
            # re-attempts every step and must not inflate the panel.
            self.metrics["radix_hit_tokens"] += radix_matched
            self.metrics["host_hit_tokens"] += host_matched
            if self.host_tier is not None and req.lora_idx == 0:
                if host_matched:
                    REGISTRY.inc(obs_names.KVC_TIER_HITS_TOTAL,
                                 tier="host")
                elif radix_matched:
                    REGISTRY.inc(obs_names.KVC_TIER_HITS_TOTAL,
                                 tier="device")
                else:
                    REGISTRY.inc(obs_names.KVC_TIER_MISSES_TOTAL)
        if blocked:
            # Every still-queued request sat this step out for a capacity
            # reason — the excess-wait metric must not count it.
            for r in self.waiting:
                r.blocked_steps += 1

    # hot_path
    def _promote_host(self, req: "Request", matched: int,
                      shared_pages: List[int]):
        """Extend a radix hit from the host spill tier: promoted pages
        move onto freshly allocated device pages and enter the radix
        cache, so this request — and every later one — device-hits
        them. Tier hit/miss accounting lives here (the one admission
        site where both tiers are consulted)."""
        h_tokens, h_pages, new_cache = self.host_tier.promote_to_device(
            req.prompt[:-1], matched, self._alloc, self.cache,
            release_fn=self.allocator.release)
        if h_tokens:
            self.cache = new_cache
            # The radix insert takes the cache's own reference on the
            # promoted pages (share()) — the request's ref stays
            # separate, exactly like a radix hit. Token accounting is
            # the CALLER's, on admission success only.
            self.radix.insert(req.prompt[:matched + h_tokens],
                              shared_pages + h_pages)
            shared_pages = shared_pages + h_pages
            matched += h_tokens
        self._publish_tier_gauges()
        return matched, shared_pages

    def _alloc(self, n: int) -> Optional[List[int]]:
        if n <= 0:
            return []
        pages = self.allocator.alloc(n)
        if pages is None and self.radix is not None:
            self.radix.evict(
                n - self.allocator.free_pages,
                on_evict=(self._spill_evicted if self.host_tier is not None
                          else None))
            pages = self.allocator.alloc(n)
            if self.host_tier is not None:
                self._publish_tier_gauges()
        return pages

    def _spill_evicted(self, prefix_tokens: List[int],
                       page_ids: List[int]) -> None:
        """Radix eviction hook: copy the evicted leaf's device pages into
        the host tier BEFORE their allocator release (device contents are
        still valid here; ids may recycle right after).

        Pages a RUNNING request still pins (refcount > 1: the cache's
        ref plus the request's) are NOT spilled: they stay device-
        resident and re-enter the radix when that request finishes —
        spilling a copy would leave the same content resident in both
        tiers, breaking the exactly-one-tier contract. A request's
        match/share always takes a PREFIX of a node's pages, so the
        pinned region is a prefix of ``page_ids`` and the free tail is
        contiguous."""
        k = 0
        while k < len(page_ids) \
                and self.allocator.refcount(page_ids[k]) > 1:
            k += 1
        if k == len(page_ids):
            return
        self.host_tier.spill_from_device(prefix_tokens, page_ids[k:],
                                         self.cache)

    def _publish_tier_gauges(self) -> None:
        if self.radix is None or self.host_tier is None:
            return
        pages = self.radix.cached_pages
        per_page = ((self.cache.k_pages.nbytes + self.cache.v_pages.nbytes)
                    / max(1, self.cache.num_pages))
        REGISTRY.set_gauge(obs_names.KVC_TIER_PAGES, float(pages),
                           tier="device")
        REGISTRY.set_gauge(obs_names.KVC_TIER_BYTES,
                           float(pages * per_page), tier="device")

    def prefix_peek(self, prompt: List[int]) -> int:
        """Advisory total prefix-hit depth (device radix + host tier)
        this prompt would get at admission. Read cross-thread by the
        admission TTFT predictor — pure dict walks, best-effort: a stale
        or zero answer only skews one prediction, never correctness."""
        if self.radix is None or len(prompt) < 2:
            return 0
        try:
            m = self.radix.peek(prompt[:-1])
            if self.host_tier is not None:
                m += self.host_tier.peek(prompt[:-1], m)
            return m
        except Exception:  # noqa: BLE001 — racy read, degrade to miss
            return 0

    # ---- ragged unified prefill/decode step ----

    def _unified_eligible(self) -> bool:
        """True when this step should run ONE ragged dispatch serving the
        whole batch (prefill chunks + decode steps together). Pure-decode
        batches return False — the fused multi-step scan (zero host syncs
        per window) beats a host-synced ragged step there."""
        if self.cfg.ragged == "off" or self.cfg.speculative != "off":
            return False
        if not any(r.state == "prefill" for r in self.running):
            return False
        if any(r.lora_idx for r in self.running):
            # lora_delta gathers adapters per batch ROW; the packed batch
            # axis is 1, so adapter-mixed batches keep the split paths.
            return False
        return True

    # bucket_fn
    def _token_bucket(self, n: int) -> int:
        """Packed-token bucket: next power of two (≥ 8), so compile
        variety stays at log2(max_batch × prefill_chunk) programs."""
        b = 8
        while b < n:
            b *= 2
        return b

    def _get_ragged_fn(self, R: int, T: int):
        """One jitted ragged forward per (row bucket, packed-token
        bucket). The cache key carries the kernel's grid revision so a
        cache warmed for one grid (PR-7 token grid vs the round-16
        block-ragged tile grid) can never alias programs compiled for
        the other."""
        from rbg_tpu.ops.pallas.ragged_attention_kernel import \
            RAGGED_GRID_REV
        fn = self._ragged_fn_cache.get((R, T, RAGGED_GRID_REV))
        if fn is None:
            import functools
            base = functools.partial(forward_ragged, cfg=self.mcfg,
                                     use_pallas=self.cfg.use_pallas,
                                     max_q_len=self.cfg.prefill_chunk)

            def wrapped(params, tokens, positions, token_mask, row_ids,
                        kv_lens, page_table, k_pages, v_pages, k_scales,
                        v_scales):
                return base(params, tokens=tokens, positions=positions,
                            token_mask=token_mask, row_ids=row_ids,
                            kv_lens=kv_lens, page_table=page_table,
                            k_pages=k_pages, v_pages=v_pages,
                            k_scales=k_scales, v_scales=v_scales)

            wrapped.__name__ = PROGRAM_RAGGED_FWD   # jitwatch catalog name
            donate = (7, 8, 9, 10) if self.cache.quantized else (7, 8)
            fn = jax.jit(wrapped, donate_argnums=donate)
            self._ragged_fn_cache[(R, T, RAGGED_GRID_REV)] = fn
        return fn

    def warm_ragged(self) -> int:
        """Pre-compile every ragged unified program shape (row bucket ×
        packed-token bucket) with an all-pad dispatch: token_mask is all
        False so every KV write drops and the pool round-trips through
        the donated buffers unchanged. A shape first hit mid-serving
        stalls every in-flight request for the compile — same rationale
        as _BatchService.warmup, which calls this. Must run while the
        engine is IDLE (no in-flight requests): the warm dispatches
        mutate the cache from the calling thread, outside the loop
        thread's single-writer discipline. Returns the number of
        programs compiled."""
        if (self.cfg.ragged == "off" or self.cfg.speculative != "off"
                or self.cfg.mode == "decode"):
            return 0
        P = self.cfg.max_pages_per_seq
        n = 0
        buckets = sorted({self._bucket(b)
                          for b in range(1, self.cfg.max_batch + 1)})
        for R in buckets:
            t = 8
            t_max = self._token_bucket(R * self.cfg.prefill_chunk)
            while True:
                fn = self._get_ragged_fn(R, t)
                _, kp, vp, ksc, vsc = fn(
                    self.params,
                    jnp.zeros((1, t), jnp.int32),
                    jnp.full((1, t), -1, jnp.int32),       # all pad
                    jnp.zeros((1, t), bool),
                    jnp.zeros((t,), jnp.int32),
                    jnp.zeros((R,), jnp.int32),
                    jnp.zeros((R, P), jnp.int32),
                    self.cache.k_pages, self.cache.v_pages,
                    self.cache.k_scales, self.cache.v_scales)
                self.cache = PagedKVCache(k_pages=kp, v_pages=vp,
                                          k_scales=ksc, v_scales=vsc)
                n += 1
                if t >= t_max:
                    break
                t *= 2
        return n

    def warm_join_windows(self) -> int:
        """Pre-compile the K=1 'early-exit' variant of every PLAIN fused
        decode program compiled so far (same bucket and sampling flags,
        window length 1). _decode_window shortens to 1 exactly on the
        join-latency path, so a mid-serving compile there would stall
        every in-flight request — the hazard warm_ragged documents —
        right when this feature is trying to cut latency. Exotic
        variants (penalties/logprobs/LoRA/grammar) stay lazy, as they do
        for every other program. Same idle-engine requirement as
        warm_ragged (the dispatches mutate the cache from the calling
        thread). Returns the number of programs compiled."""
        if self.cfg.multi_step == 1 or self.cfg.ragged == "off":
            return 0   # the window never shortens (see _decode_window)
        P = self.cfg.max_pages_per_seq
        n = 0
        for (B, pen, lp, tpmp, la, gr, K) in list(self._dec_fn_cache):
            if K == 1 or pen or lp or la or gr:
                continue
            if (B, pen, lp, tpmp, la, gr, 1) in self._dec_fn_cache:
                continue
            temps, ks, tps, mps, seeds, rids, _, _, _ = \
                self._sampling_rows([], B)
            fn = self._get_decode_fn(B, pen, lp, tpmp, la, gr, K=1)
            # mask all-False: write_ok is False everywhere, so no KV slot
            # is written and pos/kvl never advance — the donated pool
            # buffers round-trip unchanged (tok/pos/kvl/limit are
            # separate arrays: pos and kvl are donated, tok is not).
            _, _, _, _, _, kp, vp, ksc, vsc, _, _ = fn(
                self.params, jnp.zeros(B, jnp.int32),
                jnp.zeros(B, jnp.int32), jnp.zeros(B, jnp.int32),
                jnp.zeros((B, P), jnp.int32), jnp.zeros((B, 1), bool),
                jnp.zeros(B, jnp.int32),
                self.cache.k_pages, self.cache.v_pages,
                self.cache.k_scales, self.cache.v_scales,
                row_keys(seeds, self._sample_base, rids),
                jnp.asarray(temps), jnp.asarray(ks), jnp.asarray(tps),
                jnp.asarray(mps))
            self.cache = PagedKVCache(k_pages=kp, v_pages=vp,
                                      k_scales=ksc, v_scales=vsc)
            n += 1
        return n

    def warm_decode(self) -> int:
        """Pre-compile the PLAIN fused decode program (no penalties /
        logprobs / LoRA / grammar) for every decode bucket × top-p
        variant at the full multi_step window. The jitwatch sentry
        surfaced this gap: warm_ragged covers the unified forward and
        warm_join_windows the K=1 variants, but the full-window decode
        program itself compiled lazily on the first pure-decode batch —
        stalling every in-flight request mid-serving. Exotic variants
        stay lazy (same policy as warm_join_windows). Same idle-engine
        requirement as warm_ragged (the warm dispatches mutate the cache
        from the calling thread). Returns the number of programs
        compiled."""
        if self.cfg.mode == "prefill" or self.cfg.speculative != "off":
            return 0   # no fused decode path to warm
        P = self.cfg.max_pages_per_seq
        K = self.cfg.multi_step
        n = 0
        buckets = sorted({self._bucket(b)
                          for b in range(1, self.cfg.max_batch + 1)})
        for B in buckets:
            for tpmp in (False, True):
                if (B, False, False, tpmp, False, False, K) \
                        in self._dec_fn_cache:
                    continue
                temps, ks, tps, mps, seeds, rids, _, _, _ = \
                    self._sampling_rows([], B)
                fn = self._get_decode_fn(B, False, False, tpmp, False,
                                         False, K=K)
                # mask all-False: no KV slot is written and pos/kvl never
                # advance — the donated pool buffers round-trip unchanged
                # (see warm_join_windows).
                _, _, _, _, _, kp, vp, ksc, vsc, _, _ = fn(
                    self.params, jnp.zeros(B, jnp.int32),
                    jnp.zeros(B, jnp.int32), jnp.zeros(B, jnp.int32),
                    jnp.zeros((B, P), jnp.int32), jnp.zeros((B, K), bool),
                    jnp.zeros(B, jnp.int32),
                    self.cache.k_pages, self.cache.v_pages,
                    self.cache.k_scales, self.cache.v_scales,
                    row_keys(seeds, self._sample_base, rids),
                    jnp.asarray(temps), jnp.asarray(ks), jnp.asarray(tps),
                    jnp.asarray(mps))
                self.cache = PagedKVCache(k_pages=kp, v_pages=vp,
                                          k_scales=ksc, v_scales=vsc)
                n += 1
        return n

    def warm_samplers(self) -> int:
        """Pre-compile the host-path sampler (prefill finish + unified
        emission) for every sample-row bucket × top-p variant. One jitted
        program per (pen, lp, tpmp) — but XLA compiles per SHAPE under
        that wrapper, so each bucket is its own compile; a first-hit
        mid-serving stalls the step exactly like an unwarmed forward.
        Penalties/logprobs variants stay lazy (warm_join_windows
        rationale). Returns the number of programs compiled."""
        if self.cfg.mode == "decode":
            return 0   # decode-only workers sample inside the fused scan
        V = self.mcfg.vocab_size
        n = 0
        buckets = sorted({self._bucket(b)
                          for b in range(1, self.cfg.max_batch + 1)})
        for B in buckets:
            for tpmp in (False, True):
                temps, ks, tps, mps, seeds, rids, _, _, _ = \
                    self._sampling_rows([], B)
                keys = step_keys(row_keys(seeds, self._sample_base, rids),
                                 jnp.zeros(B, jnp.int32))
                fn = self._get_sampler(False, False, tpmp)
                toks, _ = fn(jnp.zeros((B, V), jnp.float32), keys,
                             jnp.asarray(temps), jnp.asarray(ks),
                             jnp.asarray(tps), jnp.asarray(mps))
                toks.block_until_ready()
                n += 1
        return n

    def _grow_decode_pages(self, rows: List[Request]) -> None:
        """Ensure every decode row has a page for its next token (the
        unified step advances decode rows by exactly one). Preempts the
        youngest on exhaustion, mirroring the fused path — but with no
        pending device window to drain (the caller already drained)."""
        for req in sorted(rows, key=lambda r: r.t_submit):
            if req.state != "running":
                continue  # preempted earlier in this very loop
            need = (pages_for_tokens(req.seq_len + 1, self.cfg.page_size)
                    - len(req.pages))
            if need <= 0:
                continue
            extra = self._alloc(need)
            while extra is None:
                if self._preempt_youngest(exclude=req) is None:
                    break
                extra = self._alloc(need)
            if extra is None:
                self._preempt(req)
                continue
            req.pages.extend(extra)

    # hot_path
    def _unified_step(self) -> List[StepEvent]:
        """ONE ragged device dispatch for the whole batch: every
        mid-prefill row contributes its next chunk, every decoding row
        contributes one step, packed on a flat token axis with per-token
        row ids (ops/ragged_paged_attention). Sampling mirrors the legacy
        paths exactly — per-row keys are fold_in(row_key, token position),
        grammar masks apply before penalties — so outputs are
        bit-identical to the split prefill/decode programs.

        The pending fused-decode window is drained FIRST: its tokens are
        already counted in seq_len (the same invariant the runtime-LoRA
        drain protects — see _rebuild_lora_stack), so dispatching decode
        rows on top of an undrained window would double-write KV slots
        and corrupt the stream."""
        events: List[StepEvent] = list(self._drain_decode())
        decode = [r for r in self.running if r.state == "running"]
        self._grow_decode_pages(decode)

        entries = []                 # (req, start, end) — end==start: decode
        for r in self.running:
            if r.state == "prefill":
                start = r.prefill_pos
                end = min(start + self.cfg.prefill_chunk, len(r.prompt))
                entries.append((r, start, end))
            elif r.state == "running":
                entries.append((r, r.seq_len, r.seq_len))
        if not entries:
            return events

        P = self.cfg.max_pages_per_seq
        Rb = self._bucket(len(entries))
        Ttot = sum((e - s) if e > s else 1 for _, s, e in entries)
        Tb = self._token_bucket(Ttot)
        tok = np.zeros((1, Tb), np.int32)
        # Pad tokens carry position -1 — the ragged-pack pad contract
        # (ops/ragged_paged_attention): the XLA fallback's unpack routes
        # them out of its scatter and the kernel skips them outright.
        pos = np.full((1, Tb), -1, np.int32)
        tmask = np.zeros((1, Tb), bool)
        row_ids = np.zeros(Tb, np.int32)
        kvl = np.zeros(Rb, np.int32)
        table = np.zeros((Rb, P), np.int32)
        off = 0
        sample_rows = []             # (req, packed_idx, key_pos, is_decode)
        for i, (req, start, end) in enumerate(entries):
            if end > start:          # prefill chunk
                n = end - start
                tok[0, off:off + n] = req.prompt[start:end]
                pos[0, off:off + n] = np.arange(start, end, dtype=np.int32)
                kvl[i] = end
                if end == len(req.prompt):
                    # Finishing row: its first output token samples at the
                    # position right after the prompt (key rule: a token at
                    # absolute position p is keyed by p).
                    sample_rows.append((req, off + n - 1, end, False))
            else:                    # decode step: write last_token, sample
                n = 1
                tok[0, off] = req.last_token
                pos[0, off] = req.seq_len
                kvl[i] = req.seq_len + 1
                sample_rows.append((req, off, req.seq_len + 1, True))
            tmask[0, off:off + n] = True
            row_ids[off:off + n] = i
            table[i, :len(req.pages)] = req.pages
            off += n

        fn = self._get_ragged_fn(Rb, Tb)
        logits, kp, vp, ksc, vsc = fn(
            self.params, jnp.asarray(tok), jnp.asarray(pos),
            jnp.asarray(tmask), jnp.asarray(row_ids), jnp.asarray(kvl),
            jnp.asarray(table), self.cache.k_pages, self.cache.v_pages,
            self.cache.k_scales, self.cache.v_scales)
        self.cache = PagedKVCache(k_pages=kp, v_pages=vp,
                                  k_scales=ksc, v_scales=vsc)

        # Host bookkeeping for prefill rows (before emission, matching the
        # legacy order: seq_len is advanced, then the finish token emits).
        for req, start, end in entries:
            if end > start:
                req.prefill_pos = end
                req.seq_len = end
                self.metrics["prefill_tokens"] += end - start
        if not sample_rows:
            return events

        # One batched sampler dispatch for every sampling row — decode
        # steps and finishing prefills together (the _prefill_step /
        # fused-scan sampler, so outputs stay bit-identical).
        reqs = [r for r, _, _, _ in sample_rows]
        Bs = self._bucket(len(sample_rows))
        pad = Bs - len(sample_rows)
        idx = np.asarray([i for _, i, _, _ in sample_rows] + [0] * pad,
                         np.int32)
        sel = logits[0][jnp.asarray(idx)]                   # [Bs, V]
        temps, ks, tps, mps, seeds, rids, pen, lp, tpmp = \
            self._sampling_rows(reqs, Bs)
        key_pos = np.zeros(Bs, np.int32)
        for n, (_, _, kpos, _) in enumerate(sample_rows):
            key_pos[n] = kpos
        keys = step_keys(row_keys(seeds, self._sample_base, rids),
                         jnp.asarray(key_pos))
        if any(r.gstate is not None for r in reqs):
            # Host-side grammar masks (the unified step host-syncs every
            # token anyway, so tabled and table-less grammars both apply
            # the mask-then-penalties order of the host path).
            gm = np.ones((Bs, self.mcfg.vocab_size), bool)
            for n, req in enumerate(reqs):
                if req.gstate is not None:
                    gm[n] = self._gmask(req.grammar, req.gstate)
            sel = jnp.where(jnp.asarray(gm), sel, NEG_INF)
        args = [sel, keys, jnp.asarray(temps), jnp.asarray(ks),
                jnp.asarray(tps), jnp.asarray(mps)]
        if pen:
            pmask, oc_base, rep, pres, freq = self._penalty_rows(reqs, Bs)
            oc = oc_base
            for n, req in enumerate(reqs):
                np.add.at(oc[n], np.asarray(req.output, np.int64), 1)
            args += [pmask, jnp.asarray(oc), rep, pres, freq]
        toks, lps = self._get_sampler(pen, lp, tpmp)(*args)
        # One batched fetch instead of two sequential np.asarray syncs
        # (device_get resolves both leaves in a single transfer; a None
        # lps leaf passes through untouched).
        # lint: allow[jit-hygiene] the step's one intrinsic emission fetch — sampled tokens must reach the host to stream
        toks, lps = jax.device_get((toks, lps))
        for n, (req, _, _, is_decode) in enumerate(sample_rows):
            lpv = (float(lps[n]) if lps is not None and req.sampling.logprobs
                   else None)
            if is_decode:
                req.seq_len += 1
                self.metrics["decode_tokens"] += 1
            else:
                req.state = "running"
                req.t_first = time.perf_counter()
            events.append(self._emit(req, int(toks[n]), lpv))
        return events

    # ---- prefill ----

    def _prefill_step(self) -> List[StepEvent]:
        """Advance every prefilling request by one chunk — BATCHED: all
        in-flight prefills share one (B, chunk) forward (rows carry their own
        positions/lengths/page tables), so admission bursts fill the MXU
        instead of running B=1 chunks serially."""
        batch = [r for r in self.running if r.state == "prefill"]
        if not batch:
            return []
        chunk = self.cfg.prefill_chunk
        rows = []
        for req in batch:
            start = req.prefill_pos
            end = min(start + chunk, len(req.prompt))
            rows.append((req, start, end))

        B = self._bucket(len(batch))
        logits = self._run(
            tokens=[req.prompt[s:e] for req, s, e in rows],
            positions=[list(range(s, e)) for _, s, e in rows],
            lens=[e for _, _, e in rows],
            pages=[req.pages for req, _, _ in rows],
            T_bucket=chunk, B_bucket=B,
            reqs=[req for req, _, _ in rows],
        )

        finishing = []
        for i, (req, start, end) in enumerate(rows):
            req.prefill_pos = end
            req.seq_len = end
            self.metrics["prefill_tokens"] += end - start
            if end == len(req.prompt):
                finishing.append((i, end - start - 1, req))
        if not finishing:
            return []

        # One batched sample for every finishing row — a single gather +
        # sampler dispatch + host transfer (mirrors the decode path).
        Bs = self._bucket(len(finishing))
        pad = Bs - len(finishing)
        row_idx = np.asarray([i for i, _, _ in finishing] + [0] * pad, np.int32)
        tok_idx = np.asarray([j for _, j, _ in finishing] + [0] * pad, np.int32)
        sel = logits[jnp.asarray(row_idx), jnp.asarray(tok_idx)]  # [Bs, V]
        reqs = [req for _, _, req in finishing]
        temps, ks, tps, mps, seeds, rids, pen, lp, tpmp = \
            self._sampling_rows(reqs, Bs)
        poss = np.zeros(Bs, np.int32)
        for n, req in enumerate(reqs):
            poss[n] = req.seq_len  # position of the token being sampled
        keys = step_keys(row_keys(seeds, self._sample_base, rids),
                         jnp.asarray(poss))
        gr = any(r.gstate is not None for r in reqs)
        if gr:
            # First output token must already obey the grammar.
            gm = np.ones((Bs, self.mcfg.vocab_size), bool)
            for n, req in enumerate(reqs):
                if req.gstate is not None:
                    gm[n] = self._gmask(req.grammar, req.gstate)
            sel = jnp.where(jnp.asarray(gm), sel, NEG_INF)
        args = [sel, keys, jnp.asarray(temps), jnp.asarray(ks),
                jnp.asarray(tps), jnp.asarray(mps)]
        if pen:
            # First sampled token: output is empty except for pre-preemption
            # tokens folded into the prompt (counted as output by
            # _penalty_rows's oc_base).
            pmask, oc_base, rep, pres, freq = self._penalty_rows(reqs, Bs)
            args += [pmask, jnp.asarray(oc_base), rep, pres, freq]
        toks, lps = self._get_sampler(pen, lp, tpmp)(*args)
        # One batched fetch — same single-transfer emission as the
        # unified step.
        toks, lps = jax.device_get((toks, lps))
        events = []
        for n, req in enumerate(reqs):
            req.state = "running"
            req.t_first = time.perf_counter()
            events.append(self._emit(
                req, int(toks[n]),
                float(lps[n]) if lps is not None and req.sampling.logprobs
                else None))
        return events

    def _sampling_rows(self, reqs, B: int):
        """Per-row sampling arrays + static variant flags for a batch —
        the ONE gather shared by prefill finish, fused decode build, and
        the speculative verify (a new sampling knob lands here once)."""
        temps = np.zeros(B, np.float32)
        ks = np.zeros(B, np.int32)
        tps = np.ones(B, np.float32)
        mps = np.zeros(B, np.float32)
        seeds: List[Optional[int]] = [None] * B
        rids = [0] * B
        for i, r in enumerate(reqs):
            sp = r.sampling
            temps[i], ks[i], tps[i], mps[i] = (sp.temperature, sp.top_k,
                                               sp.top_p, sp.min_p)
            seeds[i], rids[i] = sp.seed, r.id
        pen = any(r.sampling.needs_penalties() for r in reqs)
        lp = any(r.sampling.logprobs for r in reqs)
        tpmp = any(r.sampling.top_p < 1.0 or r.sampling.min_p > 0.0
                   for r in reqs)
        return temps, ks, tps, mps, seeds, rids, pen, lp, tpmp

    def _lora_rows(self, reqs, B: int):
        """(lora_ids [B] or None): None when no row uses an adapter —
        callers compile the adapter-free variant in that case."""
        if self.lora_stack is None or not any(r.lora_idx for r in reqs):
            return None
        ids = np.zeros(B, np.int32)
        for i, r in enumerate(reqs):
            ids[i] = r.lora_idx
        return jnp.asarray(ids)

    def _penalty_rows(self, reqs, B: int):
        """Host-built penalty state: prompt-seen mask, output-count base,
        and per-row factors. [B, V] is only materialized when some request
        in the batch actually uses penalties (callers compile separate
        variants otherwise). A preempted-and-resumed request carries its
        pre-preemption output inside ``prompt`` — those tokens count as
        OUTPUT (oc_base), not prompt, so presence/frequency penalties and
        seeded reproducibility survive preemption."""
        V = self.mcfg.vocab_size
        pmask = np.zeros((B, V), bool)
        oc_base = np.zeros((B, V), np.int32)
        rep = np.ones(B, np.float32)
        pres = np.zeros(B, np.float32)
        freq = np.zeros(B, np.float32)
        for n, req in enumerate(reqs):
            sp = req.sampling
            pmask[n, np.asarray(req.prompt[:req.orig_prompt_len],
                                np.int64)] = True
            np.add.at(oc_base[n],
                      np.asarray(req.prompt[req.orig_prompt_len:], np.int64),
                      1)
            rep[n], pres[n], freq[n] = (sp.repetition_penalty,
                                        sp.presence_penalty,
                                        sp.frequency_penalty)
        return (jnp.asarray(pmask), oc_base, jnp.asarray(rep),
                jnp.asarray(pres), jnp.asarray(freq))

    # hot_path
    def _get_sampler(self, pen: bool, lp: bool, tpmp: bool = True):
        fn = self._samplers.get((pen, lp, tpmp))
        if fn is None:
            if pen:
                def f(sel, keys, temps, ks, tps, mps, pmask, ocounts,
                      rep, pres, freq):
                    return sample(sel, keys, temps, ks, tps, mps,
                                  prompt_mask=pmask, out_counts=ocounts,
                                  rep=rep, pres=pres, freq=freq,
                                  want_logprobs=lp, use_top_p_min_p=tpmp)
            else:
                def f(sel, keys, temps, ks, tps, mps):
                    return sample(sel, keys, temps, ks, tps, mps,
                                  want_logprobs=lp, use_top_p_min_p=tpmp)
            f.__name__ = PROGRAM_SAMPLER   # jitwatch catalog name
            fn = jax.jit(f)
            self._samplers[(pen, lp, tpmp)] = fn
        return fn

    # ---- decode ----

    def _pending_counts(self) -> Dict[int, int]:
        """id(req) → number of un-emitted tokens awaiting fetch."""
        if self._dec is None or self._dec["pending"] is None:
            return {}
        rows, _, _, valid = self._dec["pending"]
        return {id(r): v for r, v in zip(rows, valid)}

    def _decode_batch(self) -> List[Request]:
        """Running requests worth dispatching. Rows whose length budget is
        already consumed by pending (un-emitted) tokens are excluded: they
        can only finish, and dispatching them would write KV tokens past
        prompt+max_new_tokens — potentially past max_seq_len."""
        pend = self._pending_counts()
        out = []
        for r in self.running:
            if r.state != "running":
                continue
            if (r.gstate is not None and self.cfg.speculative != "ngram"
                    and not self._row_fusable(r)):
                # Table-less grammar rows (pushdown JSON / budget-exceeded
                # / tables off) decode via the host-synced step; tabled
                # grammars join the fused window.
                continue
            if len(r.output) + pend.get(id(r), 0) >= r.sampling.max_new_tokens:
                continue
            out.append(r)
        return out

    def _emit_pending(self, pending) -> List[StepEvent]:
        rows, toks_dev, lp_dev, valid = pending
        vals = np.asarray(toks_dev)          # [K, B] — the one host sync
        lpv = np.asarray(lp_dev) if lp_dev is not None else None
        events = []
        for i, req in enumerate(rows):
            for k in range(valid[i]):
                if req.state != "running":
                    break                    # stop token cut the window short
                self.metrics["decode_tokens"] += 1
                lp = (float(lpv[k, i])
                      if lpv is not None and req.sampling.logprobs else None)
                events.append(self._emit(req, int(vals[k, i]), lp))
        return events

    def _drain_decode(self) -> List[StepEvent]:
        """Fetch + emit the pending decode tokens and discard the device
        state (forcing a rebuild). Called whenever the decode batch
        composition changes, or before preemption releases pages that host
        bookkeeping must observe consistently."""
        st, self._dec = self._dec, None
        if st is None or st["pending"] is None:
            return []
        return self._emit_pending(st["pending"])

    # hot_path
    def _decode_window(self) -> int:
        """Fused-scan window length for THIS step. Continuous batching:
        when a join is possible and work is waiting (a service submission
        beyond this step's admissions, or an engine-queued request while a
        batch slot is free — i.e. page-blocked), the window shortens to 1
        so the scan 'exits early' and absorbs the join next step instead
        of making it wait out a full multi_step window."""
        K = self.cfg.multi_step
        if K == 1 or self.cfg.ragged == "off":
            return K   # 'off' IS the window-boundary baseline behavior
        if (len(self.running) < self.cfg.max_batch
                and (self.join_hint or self.waiting)):
            # A join is actually possible (free slot) and work is waiting
            # (page-blocked in the engine queue, or still queued at the
            # service): short windows surface finishes — and the pages
            # they release — at step granularity so the join lands next
            # step. When the batch is FULL, shortening buys nothing and
            # costs the window's dispatch amortization — keep K.
            return 1
        return K

    def _get_decode_fn(self, B: int, pen: bool, lp: bool,
                       tpmp: bool = True, la: bool = False,
                       gr: bool = False, K: Optional[int] = None):
        """One fused jitted program per (decode bucket, penalties-active,
        logprobs-active, grammar-active): a lax.scan window of
        ``multi_step`` iterations, each = forward + on-device sampling +
        position/length increment, with the sampled token fed straight
        back as the next iteration's input. Per-row sampling keys are
        fold_in(row_key, position) — no key-split carry, and a state
        rebuild replays the identical stream. Steady state does ZERO
        host→device transfers per window and one device→host fetch (the
        [K, B] token ids, one window late). Penalty state ([B, V] prompt
        mask + output counts), grammar state (per-row table-state id +
        the shared [S, V] transition/legality arrays), and per-step
        logprobs only exist in the variants that need them.

        The grammar variant masks logits with ``glegal[gstate]`` BEFORE
        sampling (the exact order of the host-synced path: mask, then
        penalties inside ``sample``) and transitions ``gstate =
        gnext[gstate, tok]`` on device — a constrained row costs the same
        dispatches as an unconstrained one. A −1 transition (EOS from a
        non-identity state can't happen; defensive) keeps the old state,
        mirroring ``_emit``'s keep-state-on-EOS bookkeeping."""
        if K is None:
            K = self.cfg.multi_step
        fn = self._dec_fn_cache.get((B, pen, lp, tpmp, la, gr, K))
        if fn is not None:
            return fn
        import functools
        base = functools.partial(forward_paged, cfg=self.mcfg,
                                 use_pallas=self.cfg.use_pallas)

        def fused(params, tok, pos, kvl, table, mask, limit, k_pages,
                  v_pages, k_scales, v_scales, keys, temps, ks, tps, mps,
                  pmask=None, ocounts=None, rep=None, pres=None, freq=None,
                  lora=None, lids=None, gnext=None, glegal=None,
                  gstate=None, gactive=None):
            def body(carry, _):
                tok, pos, kvl, kp, vp, ksc, vsc, oc, gs = carry
                # Rows at their length limit (mid-window finishers) stop
                # writing KV and stop advancing — their sampled values are
                # discarded host-side via the per-row valid count.
                write_ok = mask & (pos < limit)[:, None]    # [B, 1]
                logits, kp, vp, ksc, vsc = base(
                    params, tokens=tok[:, None], positions=pos[:, None],
                    token_mask=write_ok, kv_lens=kvl, page_table=table,
                    k_pages=kp, v_pages=vp, k_scales=ksc, v_scales=vsc,
                    lora=lora, lora_ids=lids)
                pkw = (dict(prompt_mask=pmask, out_counts=oc, rep=rep,
                            pres=pres, freq=freq) if pen else {})
                lg = logits[:, 0, :]
                if gr:
                    # Grammar mask first, penalties inside sample() after —
                    # the identical order the host-synced path applies.
                    lg = jnp.where(glegal[gs] | ~gactive[:, None],
                                   lg, NEG_INF)
                # Key by the OUTPUT token's position (pos + 1): the input
                # token at ``pos`` was itself sampled with key fold_in(row,
                # pos) — prefill keys its first token by seq_len, so reusing
                # ``pos`` here would replay that exact Gumbel noise.
                toks, lps = sample(lg, step_keys(keys, pos + 1),
                                   temps, ks, tps, mps, want_logprobs=lp,
                                   use_top_p_min_p=tpmp, **pkw)
                active = write_ok[:, 0]
                if pen:
                    oc = oc.at[jnp.arange(oc.shape[0]), toks].add(
                        active.astype(jnp.int32))
                if gr:
                    ns = gnext[gs, toks]
                    gs = jnp.where(gactive & active & (ns >= 0), ns, gs)
                pos = jnp.where(active, pos + 1, pos)
                kvl = jnp.where(active, kvl + 1, kvl)
                tok = jnp.where(active, toks, tok)
                ys = (toks, lps) if lp else toks
                return (tok, pos, kvl, kp, vp, ksc, vsc, oc, gs), ys

            oc0 = ocounts if pen else jnp.zeros((), jnp.int32)
            gs0 = gstate if gr else jnp.zeros((), jnp.int32)
            carry, ys = jax.lax.scan(
                body, (tok, pos, kvl, k_pages, v_pages, k_scales, v_scales,
                       oc0, gs0), None, length=K)
            tok, pos, kvl, kp, vp, ksc, vsc, oc, gs = carry
            toks_seq, lp_seq = ys if lp else (ys, None)
            return (toks_seq, lp_seq, tok, pos, kvl, kp, vp, ksc, vsc, oc,
                    gs)

        # tok is NOT donated: the pending fetch reads last window's output
        # after it has been fed back as this window's input. keys is reused
        # across windows (constant); ocounts is carried and donated.
        donate = [2, 3]  # pos, kvl
        donate += [7, 8, 9, 10] if self.cache.quantized else [7, 8]
        if pen:
            donate.append(17)  # ocounts
        fused.__name__ = PROGRAM_FUSED_DECODE   # jitwatch catalog name
        fn = jax.jit(fused, donate_argnums=tuple(donate))
        self._dec_fn_cache[(B, pen, lp, tpmp, la, gr, K)] = fn
        return fn

    def _build_decode_state(self, batch: List[Request]) -> dict:
        B = self._bucket(len(batch))
        P = self.cfg.max_pages_per_seq
        tok = np.zeros(B, np.int32)
        pos = np.zeros(B, np.int32)
        kvl = np.zeros(B, np.int32)
        mask = np.zeros((B, 1), bool)
        limit = np.zeros(B, np.int32)
        table = np.zeros((B, P), np.int32)
        temps, ks, tps, mps, seeds, rids, pen, lp, tpmp = \
            self._sampling_rows(batch, B)
        for i, r in enumerate(batch):
            tok[i] = r.last_token
            pos[i] = r.seq_len
            kvl[i] = r.seq_len + 1
            mask[i, 0] = True
            limit[i] = r.max_len()
            table[i, :len(r.pages)] = r.pages
        lids = self._lora_rows(batch, B)
        st = {
            "rows": list(batch), "B": B, "pen": pen, "lp": lp,
            "tpmp": tpmp, "lids": lids,
            "tok": jnp.asarray(tok), "pos": jnp.asarray(pos),
            "kvl": jnp.asarray(kvl), "mask": jnp.asarray(mask),
            "limit": jnp.asarray(limit),
            "temps": jnp.asarray(temps), "ks": jnp.asarray(ks),
            "tps": jnp.asarray(tps), "mps": jnp.asarray(mps),
            "keys": row_keys(seeds, self._sample_base, rids),
            "table_np": table, "table": jnp.asarray(table),
            "pending": None,
        }
        if pen:
            pmask, oc, rep, pres, freq = self._penalty_rows(batch, B)
            for i, r in enumerate(batch):
                np.add.at(oc[i], np.asarray(r.output, np.int64), 1)
            st.update(pmask=pmask, ocounts=jnp.asarray(oc),
                      rep=rep, pres=pres, freq=freq)
        gr_rows = [r for r in batch if r.gstate is not None]
        st["gr"] = bool(gr_rows)
        if gr_rows:
            # Device-resident grammar decode: per-row table-state ids into
            # the stacked [S, V] tables. A rebuild recovers the device
            # state exactly from req.gstate — host bookkeeping (_emit)
            # advances it token-by-token, and every engine gstate is
            # whole-token-reachable, so the lookup cannot miss.
            gnext, glegal, offsets = self._device_grammar_tables(
                [r.grammar for r in gr_rows])
            gstate = np.zeros(B, np.int32)
            gactive = np.zeros(B, bool)
            for i, r in enumerate(batch):
                if r.gstate is not None:
                    t = self._grammar_table(r.grammar)
                    gstate[i] = offsets[id(r.grammar)] + t.state_ids[r.gstate]
                    gactive[i] = True
            st.update(gnext=gnext, glegal=glegal,
                      gstate=jnp.asarray(gstate),
                      gactive=jnp.asarray(gactive))
        return st

    def _decode_step(self) -> List[StepEvent]:
        if self.cfg.speculative == "ngram":
            # Speculative mode: the host-synced verify step owns the whole
            # batch (drafts, grammar masks, and penalties together —
            # penalized/grammar rows simply never draft).
            events = self._drain_decode()
            return events + self._spec_decode_step()
        if any(r.gstate is not None and not self._row_fusable(r)
               for r in self.running if r.state == "running"):
            # Mixed traffic: ONLY table-less grammar rows pay the
            # per-token host-synced step; everyone else — tabled grammar
            # rows included — keeps the fused multi-step path (its
            # _decode_batch excludes exactly the host-synced rows).
            events = self._spec_decode_step(grammar_only=True)
            return events + self._fused_decode_step()
        return self._fused_decode_step()

    # hot_path
    def _fused_decode_step(self) -> List[StepEvent]:
        events: List[StepEvent] = []
        batch = self._decode_batch()
        st = self._dec
        if st is not None and st["rows"] != batch:
            events.extend(self._drain_decode())
            st = None
            batch = self._decode_batch()
        if not batch:
            events.extend(self._drain_decode())
            return events

        # Ensure pages exist for the whole decode window; preempt the
        # youngest requests on exhaustion. Oldest-first so old requests
        # finish and release memory (deadlock-free under oversubscription).
        K = self._decode_window()
        pages_changed = False
        for req in sorted(batch, key=lambda r: r.t_submit):
            if req.state != "running":
                continue  # preempted earlier in this very loop
            horizon = min(req.seq_len + K, req.max_len())
            need = pages_for_tokens(horizon, self.cfg.page_size) - len(req.pages)
            if need > 0:
                extra = self._alloc(need)
                while extra is None:
                    # Emit in-flight tokens before any pages are released:
                    # a preempted request must not receive a stale token
                    # (and an emitted finish may free enough on its own).
                    events.extend(self._drain_decode())
                    st = None
                    if req.state != "running":
                        break  # the drain just finished THIS request
                    extra = self._alloc(need)
                    if extra is not None:
                        break
                    victim = self._preempt_youngest(exclude=req)
                    if victim is None:
                        break
                    extra = self._alloc(need)
                if req.state != "running":
                    # Finished by a pending stop token emitted in the drain:
                    # its pages are already released — growing or preempting
                    # it now would leak pages / resurrect a finished stream.
                    if extra:
                        self.allocator.release(extra)
                    continue
                if extra is None:
                    events.extend(self._drain_decode())
                    st = None
                    if req.state != "running":
                        continue
                    self._preempt(req)
                    continue
                req.pages.extend(extra)
                pages_changed = True
        batch2 = self._decode_batch()
        if batch2 != batch:
            if st is not None:
                events.extend(self._drain_decode())
                st = None
            batch = batch2
        if not batch:
            return events

        if st is None:
            st = self._dec = self._build_decode_state(batch)
        elif pages_changed:
            for i, r in enumerate(batch):
                row = st["table_np"][i]
                row[:len(r.pages)] = r.pages
                row[len(r.pages):] = 0
            st["table"] = jnp.asarray(st["table_np"])

        fn = self._get_decode_fn(st["B"], st["pen"], st["lp"],
                                 st["tpmp"], st["lids"] is not None,
                                 st["gr"], K=K)
        kw = {}
        if st["pen"]:
            kw.update(pmask=st["pmask"], ocounts=st["ocounts"],
                      rep=st["rep"], pres=st["pres"], freq=st["freq"])
        if st["lids"] is not None:
            kw.update(lora=self.lora_stack, lids=st["lids"])
        if st["gr"]:
            kw.update(gnext=st["gnext"], glegal=st["glegal"],
                      gstate=st["gstate"], gactive=st["gactive"])
        toks_seq, lp_seq, tok, pos, kvl, kp, vp, ksc, vsc, oc, gs = fn(
            self.params, st["tok"], st["pos"], st["kvl"], st["table"],
            st["mask"], st["limit"], self.cache.k_pages, self.cache.v_pages,
            self.cache.k_scales, self.cache.v_scales,
            st["keys"], st["temps"], st["ks"], st["tps"], st["mps"], **kw)
        self.cache = PagedKVCache(k_pages=kp, v_pages=vp,
                                  k_scales=ksc, v_scales=vsc)
        st["tok"], st["pos"], st["kvl"] = tok, pos, kvl
        if st["pen"]:
            st["ocounts"] = oc
        if st["gr"]:
            st["gstate"] = gs
        valid = []
        for req in batch:
            valid.append(min(K, req.max_len() - req.seq_len))
            req.seq_len = min(req.seq_len + K, req.max_len())

        prev, st["pending"] = st["pending"], (list(batch), toks_seq, lp_seq,
                                              valid)
        if prev is not None:
            events.extend(self._emit_pending(prev))
        return events

    # ---- speculative decode (prompt-lookup drafting) ----

    def _ensure_ngram(self, req: Request):
        """Lazily build/extend the request's n-gram index over its logical
        sequence (prompt + output — stable across preemption, which only
        moves tokens between the two)."""
        from rbg_tpu.engine.spec import NGramIndex
        if req.ngram is None:
            req.ngram = NGramIndex(self.cfg.spec_ngram)
        idx = req.ngram
        have = len(idx.tokens)
        total = req.total_len
        if have < total:
            seq = req.prompt + req.output
            idx.extend(seq[have:total])

    def _get_spec_fn(self, B: int, lp: bool, tpmp: bool = True,
                     pen: bool = False, gr: bool = False, la: bool = False):
        """One jitted verify program per (bucket, logprobs, top-p, pen,
        grammar): a (B, K+1) paged forward + per-position sampling, keys
        fold_in(row, pos+1) — the same keys the sequential path would use,
        so accepted tokens are exactly what non-speculative decoding would
        have produced. Penalized rows use host-built counts (constant
        across the window — those rows never draft, so only their slot-0
        sample is consumed). Grammar rows get per-slot allowed-token masks
        computed host-side along the draft path."""
        key = (B, lp, tpmp, pen, gr, la)
        fn = self._spec_fn_cache.get(key)
        if fn is not None:
            return fn
        import functools
        base = functools.partial(forward_paged, cfg=self.mcfg,
                                 use_pallas=self.cfg.use_pallas)

        def specfn(params, tok, pos, mask, kvl, table, k_pages, v_pages,
                   k_scales, v_scales, keys, temps, ks, tps, mps,
                   pmask=None, ocounts=None, rep=None, pres=None, freq=None,
                   gmasks=None, lora=None, lids=None):
            logits, kp, vp, ksc, vsc = base(
                params, tokens=tok, positions=pos, token_mask=mask,
                kv_lens=kvl, page_table=table, k_pages=k_pages,
                v_pages=v_pages, k_scales=k_scales, v_scales=v_scales,
                lora=lora, lora_ids=lids)
            pkw = (dict(prompt_mask=pmask, out_counts=ocounts, rep=rep,
                        pres=pres, freq=freq) if pen else {})

            def samp(lg_t, pos_t, gm_t):    # [B, V], [B], [B, V]
                if gr:
                    lg_t = jnp.where(gm_t, lg_t, NEG_INF)
                return sample(lg_t, step_keys(keys, pos_t + 1),
                              temps, ks, tps, mps, want_logprobs=lp,
                              use_top_p_min_p=tpmp, **pkw)

            gm = gmasks if gr else jnp.zeros(
                (logits.shape[0], logits.shape[1], 1), bool)
            toks, lps = jax.vmap(samp, in_axes=(1, 1, 1))(logits, pos, gm)
            return toks, lps, kp, vp, ksc, vsc  # toks/lps: [T, B]

        specfn.__name__ = PROGRAM_SPEC_VERIFY   # jitwatch catalog name
        donate = (6, 7, 8, 9) if self.cache.quantized else (6, 7)
        fn = jax.jit(specfn, donate_argnums=donate)
        self._spec_fn_cache[key] = fn
        return fn

    def _spec_decode_step(self, grammar_only: bool = False) -> List[StepEvent]:
        events: List[StepEvent] = []
        batch = [r for r in self.running if r.state == "running"
                 and (not grammar_only
                      or (r.gstate is not None and not self._row_fusable(r)))
                 and len(r.output) < r.sampling.max_new_tokens]
        if not batch:
            return events
        K = self.cfg.spec_k if self.cfg.speculative == "ngram" else 0
        ps = self.cfg.page_size
        drafts: Dict[int, List[int]] = {}
        gmask_rows: Dict[int, list] = {}
        # Draft + grow pages, oldest-first (preempt youngest on exhaustion;
        # a row sheds its drafts before anyone gets preempted for them).
        # Penalized rows never draft (their counts are sequential); grammar
        # rows draft along the automaton — masks are computed assuming the
        # draft prefix is accepted, which holds for every accepted prefix.
        for req in sorted(batch, key=lambda r: r.t_submit):
            if req.state != "running":
                continue
            cap = min(K, req.sampling.max_new_tokens - len(req.output) - 1,
                      self.cfg.max_seq_len - req.seq_len - 1)
            if cap > 0 and not req.sampling.needs_penalties():
                self._ensure_ngram(req)
                d = req.ngram.draft(cap)
            else:
                d = []
            if req.gstate is not None:
                g = req.grammar
                s = req.gstate
                masks = [self._gmask(g, s)]
                kept = []
                for dt in d:
                    ns = g.advance_token(s, dt)
                    if ns is None:
                        break           # draft leaves the grammar — cut here
                    kept.append(dt)
                    masks.append(self._gmask(g, ns))
                    s = ns
                d = kept
                gmask_rows[id(req)] = masks
            while True:
                need = (pages_for_tokens(req.seq_len + 1 + len(d), ps)
                        - len(req.pages))
                if need <= 0:
                    break
                extra = self._alloc(need)
                if extra is not None:
                    req.pages.extend(extra)
                    break
                if d:
                    d = []          # shed drafts before preempting others
                    continue
                if self._preempt_youngest(exclude=req) is None:
                    self._preempt(req)
                    break
            if req.state == "running":
                drafts[id(req)] = d
        batch = [r for r in batch if r.state == "running"]
        if not batch:
            return events

        B = self._bucket(len(batch))
        T = K + 1
        P = self.cfg.max_pages_per_seq
        tok = np.zeros((B, T), np.int32)
        pos = np.zeros((B, T), np.int32)
        mask = np.zeros((B, T), bool)
        kvl = np.zeros(B, np.int32)
        table = np.zeros((B, P), np.int32)
        temps, ks, tps, mps, seeds, rids, pen, lp, tpmp = \
            self._sampling_rows(batch, B)
        gr = any(r.gstate is not None for r in batch)
        gmasks = (np.ones((B, T, self.mcfg.vocab_size), bool)
                  if gr else None)
        for i, r in enumerate(batch):
            d = drafts[id(r)]
            tok[i, 0] = r.last_token
            tok[i, 1:1 + len(d)] = d
            pos[i, :] = r.seq_len + np.arange(T)
            mask[i, :1 + len(d)] = True
            kvl[i] = r.seq_len + 1 + len(d)
            table[i, :len(r.pages)] = r.pages
            if gr and id(r) in gmask_rows:
                for t, m in enumerate(gmask_rows[id(r)]):
                    gmasks[i, t] = m
        kw = {}
        if pen:
            pmask, oc, rep, pres, freq = self._penalty_rows(batch, B)
            for i, r in enumerate(batch):
                np.add.at(oc[i], np.asarray(r.output, np.int64), 1)
            kw.update(pmask=pmask, ocounts=jnp.asarray(oc), rep=rep,
                      pres=pres, freq=freq)
        if gr:
            kw["gmasks"] = jnp.asarray(gmasks)
        lids = self._lora_rows(batch, B)
        if lids is not None:
            kw.update(lora=self.lora_stack, lids=lids)
        fn = self._get_spec_fn(B, lp, tpmp, pen, gr, lids is not None)
        toks_out, lps_out, kp, vp, ksc, vsc = fn(
            self.params, jnp.asarray(tok), jnp.asarray(pos),
            jnp.asarray(mask), jnp.asarray(kvl), jnp.asarray(table),
            self.cache.k_pages, self.cache.v_pages,
            self.cache.k_scales, self.cache.v_scales,
            row_keys(seeds, self._sample_base, rids),
            jnp.asarray(temps), jnp.asarray(ks), jnp.asarray(tps),
            jnp.asarray(mps), **kw)
        self.cache = PagedKVCache(k_pages=kp, v_pages=vp,
                                  k_scales=ksc, v_scales=vsc)
        vals = np.asarray(toks_out)                       # [T, B]
        lpv = np.asarray(lps_out) if lps_out is not None else None
        self.metrics["spec_steps"] += 1
        for i, req in enumerate(batch):
            d = drafts[id(req)]
            m = 0
            while m < len(d) and int(vals[m, i]) == d[m]:
                m += 1
            # d_0..d_{m-1} verified; vals[m] is the true next token at the
            # first mismatch (or the bonus token when every draft held).
            self.metrics["spec_drafted"] += len(d)
            self.metrics["spec_accepted"] += m
            emit_n = m + 1
            req.seq_len += emit_n   # KV valid through the last GOOD input
            for t in range(emit_n):
                if req.state != "running":
                    break           # stop token cut the window short
                self.metrics["decode_tokens"] += 1
                lpt = (float(lpv[t, i])
                       if lpv is not None and req.sampling.logprobs else None)
                events.append(self._emit(req, int(vals[t, i]), lpt))
        return events

    def _emit(self, req: Request, tok: int,
              logprob: Optional[float] = None) -> StepEvent:
        req.output.append(tok)
        if req.ngram is not None:
            req.ngram.append(tok)
        if req.gstate is not None and req.grammar is not None:
            nxt = req.grammar.advance_token(req.gstate, tok)
            if nxt is not None:     # defensively keep old state on EOS etc.
                req.gstate = nxt
        req.last_token = tok
        finished = (
            len(req.output) >= req.sampling.max_new_tokens
            or (req.sampling.stop_token is not None and tok == req.sampling.stop_token)
        )
        if finished:
            self._finish(req)
        return StepEvent(req.id, tok, finished, logprob=logprob)

    # ---- lifecycle ----

    def _finish(self, req: Request):
        req.state = "finished"
        self.running = [r for r in self.running if r is not req]
        if self.cfg.mode == "prefill":
            # Disaggregated prefill: the pages ARE the product — the PD layer
            # exports them to a decode peer, then calls release_request().
            req.state = "exported"
            return
        if self.radix is not None and req.lora_idx == 0:
            # Cache the full sequence (prompt + output) for future prefixes
            # (base-model requests only — adapter KV must not cross-match).
            self.radix.insert(req.prompt + req.output[:-1], req.pages)
            if self.host_tier is not None:
                self._publish_tier_gauges()
        self.allocator.release(req.pages)
        req.pages = []
        # Don't retain finished requests forever (long-running servers).
        self.requests.pop(req.id, None)

    def release_request(self, req_id: int):
        """Release an exported request's pages (prefill mode)."""
        req = self.requests.pop(req_id)
        if req.pages:
            self.allocator.release(req.pages)
            req.pages = []

    def cancel_request(self, req_id: int) -> bool:
        """Abort a request: drop it from the queues and recycle its pages.
        (Must be called from the thread driving step() — the EngineService
        routes cancellations through its loop.)"""
        req = self.requests.get(req_id)
        if req is None or req.state == "finished":
            return False
        req.state = "finished"
        self.waiting = [r for r in self.waiting if r is not req]
        self.running = [r for r in self.running if r is not req]
        if req.pages:
            self.allocator.release(req.pages)
            req.pages = []
        self.requests.pop(req_id, None)
        return True

    def _preempt(self, req: Request):
        self.metrics["preemptions"] += 1
        self.allocator.release(req.pages)
        req.pages = []
        req.state = "waiting"
        req.prefill_pos = 0
        req.seq_len = 0
        req.shared_tokens = 0
        # Re-queued: join accounting restarts from the preemption step
        # (time spent RUNNING must not read as queue wait).
        req.enqueue_step = self.metrics["steps"]
        req.blocked_steps = 0
        req.t_enqueue = time.perf_counter()
        # Restart cleanly: generated tokens so far are kept as prompt
        # extension so decoding resumes where it left off.
        if req.output:
            req.prompt = req.prompt + req.output
            req.sampling = dataclasses.replace(
                req.sampling,
                max_new_tokens=req.sampling.max_new_tokens - len(req.output))
            req.output = []
        self.running = [r for r in self.running if r is not req]
        self.waiting.insert(0, req)

    def _preempt_youngest(self, exclude: Request) -> Optional[Request]:
        candidates = [r for r in self.running if r.state == "running" and r is not exclude]
        if not candidates:
            return None
        victim = max(candidates, key=lambda r: r.t_submit)
        self._preempt(victim)
        return victim

    # ---- device dispatch ----

    # bucket_fn
    def _bucket(self, n: int) -> int:
        for b in self.cfg.decode_buckets:
            if b >= n:
                return min(b, max(self.cfg.decode_buckets))
        return max(self.cfg.decode_buckets)

    def _get_fwd(self, B: int, T: int, la: bool = False):
        key = (B, T, la)
        fn = self._fwd_cache.get(key)
        if fn is None:
            import functools
            base = functools.partial(forward_paged, cfg=self.mcfg,
                                     use_pallas=self.cfg.use_pallas)

            def wrapped(params, tokens, positions, token_mask, kv_lens,
                        page_table, k_pages, v_pages, k_scales, v_scales,
                        lora=None, lids=None):
                return base(params, tokens=tokens, positions=positions,
                            token_mask=token_mask, kv_lens=kv_lens,
                            page_table=page_table, k_pages=k_pages,
                            v_pages=v_pages, k_scales=k_scales,
                            v_scales=v_scales, lora=lora, lora_ids=lids)

            wrapped.__name__ = PROGRAM_PAGED_FWD   # jitwatch catalog name
            donate = (6, 7, 8, 9) if self.cache.quantized else (6, 7)
            fn = jax.jit(wrapped, donate_argnums=donate)
            self._fwd_cache[key] = fn
        return fn

    def _run(self, tokens, positions, lens, pages, T_bucket, B_bucket=None,
             reqs=None):
        """Pad host-side lists to (B_bucket, T_bucket) and dispatch.
        ``reqs`` (row-aligned) selects per-row LoRA adapters when given."""
        B = B_bucket or 1
        T = T_bucket
        P = self.cfg.max_pages_per_seq
        tok = np.zeros((B, T), np.int32)
        pos = np.zeros((B, T), np.int32)
        mask = np.zeros((B, T), bool)
        kvl = np.zeros((B,), np.int32)
        table = np.zeros((B, P), np.int32)
        for i, (ts, ps_, ln, pg) in enumerate(zip(tokens, positions, lens, pages)):
            tok[i, :len(ts)] = ts
            pos[i, :len(ps_)] = ps_
            mask[i, :len(ts)] = True
            kvl[i] = ln
            table[i, :len(pg)] = pg
        lids = self._lora_rows(reqs, B) if reqs is not None else None
        kw = ({"lora": self.lora_stack, "lids": lids}
              if lids is not None else {})
        fn = self._get_fwd(B, T, lids is not None)
        logits, k_pages, v_pages, k_scales, v_scales = fn(
            self.params, jnp.asarray(tok), jnp.asarray(pos), jnp.asarray(mask),
            jnp.asarray(kvl), jnp.asarray(table),
            self.cache.k_pages, self.cache.v_pages,
            self.cache.k_scales, self.cache.v_scales, **kw,
        )
        self.cache = PagedKVCache(k_pages=k_pages, v_pages=v_pages,
                                  k_scales=k_scales, v_scales=v_scales)
        return logits  # device array; callers slice what they need
