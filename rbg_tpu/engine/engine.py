"""Serving engine: continuous batching over a paged KV pool.

The data plane the control plane orchestrates — the SGLang-on-JAX-equivalent
(the reference deploys SGLang in its role pods; BASELINE.md configs). One
Engine = one model replica on one JAX program (single chip or a whole slice
via the tp/sp mesh).

Design (TPU-first):
* **Bucketed static shapes** — one compiled program per (batch, chunk)
  bucket; prefill chunks and decode steps reuse the same ``forward_paged``.
* **Host-side logistics, device-side math** — page tables/lengths are plain
  numpy handed to jit as arrays; the graph never sees Python branching.
* **Chunked prefill** — long prompts stream through a fixed-size chunk
  program, so TTFT for short prompts never waits behind a long compile.
* **Radix prefix cache** — page-granular prefix sharing with LRU eviction.
* **Preemption** — page exhaustion preempts the youngest request back to the
  waiting queue (its pages recycle; the radix cache softens the re-prefill).

Modes: ``unified`` (prefill+decode co-located), ``prefill`` (produces KV
pages + first token for a peer), ``decode`` (imports KV pages) — see
rbg_tpu.engine.pd for the disaggregated pair.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from rbg_tpu.engine.config import EngineConfig, SamplingParams
from rbg_tpu.engine.kvcache import PageAllocator, PagedKVCache, pages_for_tokens
from rbg_tpu.engine.radix_cache import RadixCache
from rbg_tpu.engine.sampler import sample
from rbg_tpu.models.llama import forward_paged, init_params


@dataclasses.dataclass
class StepEvent:
    request_id: int
    token: int
    finished: bool
    text_done: bool = False


class Request:
    _ids = itertools.count()

    def __init__(self, prompt: List[int], sampling: SamplingParams):
        self.id = next(Request._ids)
        self.prompt = list(prompt)
        self.sampling = sampling
        self.output: List[int] = []
        self.state = "waiting"          # waiting | prefill | running | finished
        self.pages: List[int] = []
        self.shared_tokens = 0          # radix-matched prefix (page-aligned)
        self.prefill_pos = 0            # next prompt index to prefill
        self.seq_len = 0                # tokens materialized in KV
        self.last_token: Optional[int] = None
        self.t_submit = time.perf_counter()
        self.t_first: Optional[float] = None

    @property
    def total_len(self) -> int:
        return len(self.prompt) + len(self.output)

    def max_len(self) -> int:
        return len(self.prompt) + self.sampling.max_new_tokens


class Engine:
    def __init__(self, cfg: EngineConfig, params: Optional[dict] = None,
                 mesh=None):
        cfg.validate()
        self.cfg = cfg
        self.mcfg = cfg.model_config
        self.mesh = mesh
        key = jax.random.key(cfg.seed)
        if params is not None:
            self.params = params
        elif cfg.checkpoint_path:
            from rbg_tpu.models.checkpoint import load_params
            self.params = load_params(cfg.checkpoint_path, self.mcfg)
        else:
            self.params = init_params(self.mcfg, key)
        self._sample_key = jax.random.key(cfg.seed + 1)

        self.cache = PagedKVCache.create(self.mcfg, cfg.num_pages, cfg.page_size,
                                         quantize=(cfg.kv_dtype == "int8"))
        self.allocator = PageAllocator(cfg.num_pages)
        self.radix = RadixCache(self.allocator, cfg.page_size) if cfg.enable_radix_cache else None

        if mesh is not None:
            self._shard_state(mesh)

        self.waiting: List[Request] = []
        self.running: List[Request] = []
        self.requests: Dict[int, Request] = {}
        self._fwd_cache: Dict[Tuple[int, int], object] = {}
        self._sampler = jax.jit(sample)
        # Fused decode path: device-resident (tok, pos, kvl, table, …) state
        # plus a one-step emission lag so host bookkeeping for step N+1
        # overlaps the device computing step N (see _decode_step).
        self._dec: Optional[dict] = None
        self._dec_key = jax.random.key(cfg.seed + 2)
        self._dec_fn_cache: Dict[int, object] = {}
        self.metrics = {"steps": 0, "decode_tokens": 0, "prefill_tokens": 0,
                        "radix_hit_tokens": 0, "preemptions": 0}

    def _shard_state(self, mesh):
        from jax.sharding import NamedSharding, PartitionSpec as P
        from rbg_tpu.parallel.sharding import param_specs, shard_pytree
        self.params = shard_pytree(
            self.params, param_specs(self.mcfg, self.params), mesh)
        page_spec = NamedSharding(mesh, P(None, None, None, "tp", None))
        self.cache = PagedKVCache(
            k_pages=jax.device_put(self.cache.k_pages, page_spec),
            v_pages=jax.device_put(self.cache.v_pages, page_spec),
            k_scales=(jax.device_put(self.cache.k_scales, page_spec)
                      if self.cache.quantized else None),
            v_scales=(jax.device_put(self.cache.v_scales, page_spec)
                      if self.cache.quantized else None),
        )

    # ---- public API ----

    def add_request(self, prompt: List[int],
                    sampling: Optional[SamplingParams] = None) -> int:
        sampling = sampling or SamplingParams()
        if len(prompt) + sampling.max_new_tokens > self.cfg.max_seq_len:
            raise ValueError(
                f"prompt+max_new_tokens {len(prompt)}+{sampling.max_new_tokens} "
                f"exceeds max_seq_len {self.cfg.max_seq_len}")
        req = Request(prompt, sampling)
        self.requests[req.id] = req
        self.waiting.append(req)
        return req.id

    def add_request_with_prefix(self, prompt: List[int],
                                sampling: Optional[SamplingParams],
                                prefix_len: int,
                                k_data, v_data) -> Optional[int]:
        """Admit a request whose first ``prefix_len`` tokens' KV arrives
        precomputed (fetched from the shared KV pool — the Mooncake-reuse
        path, keps/74): the pages are written into the local pool and
        prefill resumes at ``prefix_len``. ``prefix_len`` must be
        page-aligned and < len(prompt) (the last token always prefills for
        logits). Returns None when no pages are free (caller falls back to
        a cold prefill through the normal admission queue)."""
        sampling = sampling or SamplingParams()
        ps = self.cfg.page_size
        if prefix_len % ps or not 0 < prefix_len < len(prompt):
            raise ValueError(f"prefix_len {prefix_len} must be page-aligned "
                             f"and in (0, {len(prompt)})")
        if len(prompt) + sampling.max_new_tokens > self.cfg.max_seq_len:
            raise ValueError("prompt+max_new_tokens exceeds max_seq_len")
        need = pages_for_tokens(len(prompt) + 1, ps)
        pages = self._alloc(need)
        if pages is None:
            return None
        n_prefix = prefix_len // ps
        ids = jnp.asarray(pages[:n_prefix], jnp.int32)
        try:
            self.cache = PagedKVCache(
                k_pages=self.cache.k_pages.at[:, ids].set(
                    jnp.asarray(k_data, self.cache.k_pages.dtype)),
                v_pages=self.cache.v_pages.at[:, ids].set(
                    jnp.asarray(v_data, self.cache.v_pages.dtype)),
                k_scales=self.cache.k_scales, v_scales=self.cache.v_scales,
            )
        except (ValueError, TypeError) as e:
            # Foreign pool data (e.g. a replica with different model
            # geometry sharing the pool): the freshly allocated pages must
            # go back or every bad hit leaks them until admission wedges.
            self.allocator.release(pages)
            raise ValueError(f"prefix KV rejected: {e}") from e
        req = Request(prompt, sampling)
        req.pages = pages
        req.prefill_pos = prefix_len
        req.seq_len = prefix_len
        req.state = "prefill"
        self.requests[req.id] = req
        self.running.append(req)
        self.metrics["pool_hit_tokens"] = (
            self.metrics.get("pool_hit_tokens", 0) + prefix_len)
        return req.id

    def has_work(self) -> bool:
        return bool(self.waiting or self.running)

    def step(self) -> List[StepEvent]:
        """One scheduler iteration: admit → prefill (chunk each) → decode."""
        events: List[StepEvent] = []
        self.metrics["steps"] += 1
        self._admit()
        events.extend(self._prefill_step())
        events.extend(self._decode_step())
        return events

    def generate(self, prompts: List[List[int]],
                 sampling: Optional[SamplingParams] = None) -> List[List[int]]:
        ids = [self.add_request(p, sampling) for p in prompts]
        outputs = {i: [] for i in ids}
        while self.has_work():
            for ev in self.step():
                if ev.request_id in outputs:
                    outputs[ev.request_id].append(ev.token)
        return [outputs[i] for i in ids]

    # ---- admission ----

    def _admit(self):
        while self.waiting and len(self.running) < self.cfg.max_batch:
            req = self.waiting[0]
            matched, shared_pages = 0, []
            if self.radix is not None and req.state == "waiting":
                # Keep at least the prompt's last token for prefill (logits).
                matched, shared_pages = self.radix.match(req.prompt[:-1])
            # Admit with pages for the PROMPT + first token only — decode
            # grows page-by-page (memory oversubscription; preemption
            # reclaims on exhaustion). Reserving max_len up front would
            # forfeit continuous batching's throughput.
            need = (pages_for_tokens(len(req.prompt) + 1, self.cfg.page_size)
                    - len(shared_pages))
            pages = self._alloc(need)
            if pages is None:
                if shared_pages:
                    self.allocator.release(shared_pages)
                break  # no capacity — stay queued
            self.waiting.pop(0)
            req.pages = shared_pages + pages
            req.shared_tokens = matched
            req.prefill_pos = matched
            req.seq_len = matched
            req.state = "prefill"
            self.running.append(req)
            self.metrics["radix_hit_tokens"] += matched

    def _alloc(self, n: int) -> Optional[List[int]]:
        if n <= 0:
            return []
        pages = self.allocator.alloc(n)
        if pages is None and self.radix is not None:
            self.radix.evict(n - self.allocator.free_pages)
            pages = self.allocator.alloc(n)
        return pages

    # ---- prefill ----

    def _prefill_step(self) -> List[StepEvent]:
        """Advance every prefilling request by one chunk — BATCHED: all
        in-flight prefills share one (B, chunk) forward (rows carry their own
        positions/lengths/page tables), so admission bursts fill the MXU
        instead of running B=1 chunks serially."""
        batch = [r for r in self.running if r.state == "prefill"]
        if not batch:
            return []
        chunk = self.cfg.prefill_chunk
        rows = []
        for req in batch:
            start = req.prefill_pos
            end = min(start + chunk, len(req.prompt))
            rows.append((req, start, end))

        B = self._bucket(len(batch))
        logits = self._run(
            tokens=[req.prompt[s:e] for req, s, e in rows],
            positions=[list(range(s, e)) for _, s, e in rows],
            lens=[e for _, _, e in rows],
            pages=[req.pages for req, _, _ in rows],
            T_bucket=chunk, B_bucket=B,
        )

        finishing = []
        for i, (req, start, end) in enumerate(rows):
            req.prefill_pos = end
            req.seq_len = end
            self.metrics["prefill_tokens"] += end - start
            if end == len(req.prompt):
                finishing.append((i, end - start - 1, req))
        if not finishing:
            return []

        # One batched sample for every finishing row — a single gather +
        # sampler dispatch + host transfer (mirrors the decode path).
        Bs = self._bucket(len(finishing))
        pad = Bs - len(finishing)
        row_idx = np.asarray([i for i, _, _ in finishing] + [0] * pad, np.int32)
        tok_idx = np.asarray([j for _, j, _ in finishing] + [0] * pad, np.int32)
        sel = logits[jnp.asarray(row_idx), jnp.asarray(tok_idx)]  # [Bs, V]
        temps = np.zeros(Bs, np.float32)
        ks = np.zeros(Bs, np.int32)
        for n, (_, _, req) in enumerate(finishing):
            temps[n] = req.sampling.temperature
            ks[n] = req.sampling.top_k
        self._sample_key, sub = jax.random.split(self._sample_key)
        toks = np.asarray(self._sampler(sel, sub, jnp.asarray(temps),
                                        jnp.asarray(ks)))
        events = []
        for n, (_, _, req) in enumerate(finishing):
            req.state = "running"
            req.t_first = time.perf_counter()
            events.append(self._emit(req, int(toks[n])))
        return events

    # ---- decode ----

    def _pending_counts(self) -> Dict[int, int]:
        """id(req) → number of un-emitted tokens awaiting fetch."""
        if self._dec is None or self._dec["pending"] is None:
            return {}
        rows, _, valid = self._dec["pending"]
        return {id(r): v for r, v in zip(rows, valid)}

    def _decode_batch(self) -> List[Request]:
        """Running requests worth dispatching. Rows whose length budget is
        already consumed by pending (un-emitted) tokens are excluded: they
        can only finish, and dispatching them would write KV tokens past
        prompt+max_new_tokens — potentially past max_seq_len."""
        pend = self._pending_counts()
        out = []
        for r in self.running:
            if r.state != "running":
                continue
            if len(r.output) + pend.get(id(r), 0) >= r.sampling.max_new_tokens:
                continue
            out.append(r)
        return out

    def _emit_pending(self, pending) -> List[StepEvent]:
        rows, toks_dev, valid = pending
        vals = np.asarray(toks_dev)          # [K, B] — the one host sync
        events = []
        for i, req in enumerate(rows):
            for k in range(valid[i]):
                if req.state != "running":
                    break                    # stop token cut the window short
                self.metrics["decode_tokens"] += 1
                events.append(self._emit(req, int(vals[k, i])))
        return events

    def _drain_decode(self) -> List[StepEvent]:
        """Fetch + emit the pending decode tokens and discard the device
        state (forcing a rebuild). Called whenever the decode batch
        composition changes, or before preemption releases pages that host
        bookkeeping must observe consistently."""
        st, self._dec = self._dec, None
        if st is None or st["pending"] is None:
            return []
        return self._emit_pending(st["pending"])

    def _get_decode_fn(self, B: int):
        """One fused jitted program per decode bucket: a lax.scan window of
        ``multi_step`` iterations, each = forward + on-device sampling +
        PRNG split + position/length increment, with the sampled token fed
        straight back as the next iteration's input. Steady state does ZERO
        host→device transfers per window and one device→host fetch (the
        [K, B] token ids, one window late)."""
        fn = self._dec_fn_cache.get(B)
        if fn is not None:
            return fn
        import functools
        base = functools.partial(forward_paged, cfg=self.mcfg,
                                 use_pallas=self.cfg.use_pallas)
        K = self.cfg.multi_step

        def fused(params, tok, pos, kvl, table, mask, limit, k_pages,
                  v_pages, k_scales, v_scales, key, temps, ks):
            def body(carry, _):
                tok, pos, kvl, kp, vp, ksc, vsc, key = carry
                # Rows at their length limit (mid-window finishers) stop
                # writing KV and stop advancing — their sampled values are
                # discarded host-side via the per-row valid count.
                write_ok = mask & (pos < limit)[:, None]    # [B, 1]
                logits, kp, vp, ksc, vsc = base(
                    params, tokens=tok[:, None], positions=pos[:, None],
                    token_mask=write_ok, kv_lens=kvl, page_table=table,
                    k_pages=kp, v_pages=vp, k_scales=ksc, v_scales=vsc)
                key, sub = jax.random.split(key)
                toks = sample(logits[:, 0, :], sub, temps, ks)
                active = write_ok[:, 0]
                pos = jnp.where(active, pos + 1, pos)
                kvl = jnp.where(active, kvl + 1, kvl)
                tok = jnp.where(active, toks, tok)
                return (tok, pos, kvl, kp, vp, ksc, vsc, key), toks

            carry, toks_seq = jax.lax.scan(
                body, (tok, pos, kvl, k_pages, v_pages, k_scales, v_scales,
                       key), None, length=K)
            tok, pos, kvl, kp, vp, ksc, vsc, key = carry
            return toks_seq, tok, pos, kvl, kp, vp, ksc, vsc, key

        # tok is NOT donated: the pending fetch reads last window's output
        # after it has been fed back as this window's input.
        donate = [2, 3, 11]  # pos, kvl, key
        donate += [7, 8, 9, 10] if self.cache.quantized else [7, 8]
        fn = jax.jit(fused, donate_argnums=tuple(donate))
        self._dec_fn_cache[B] = fn
        return fn

    def _build_decode_state(self, batch: List[Request]) -> dict:
        B = self._bucket(len(batch))
        P = self.cfg.max_pages_per_seq
        tok = np.zeros(B, np.int32)
        pos = np.zeros(B, np.int32)
        kvl = np.zeros(B, np.int32)
        mask = np.zeros((B, 1), bool)
        limit = np.zeros(B, np.int32)
        temps = np.zeros(B, np.float32)
        ks = np.zeros(B, np.int32)
        table = np.zeros((B, P), np.int32)
        for i, r in enumerate(batch):
            tok[i] = r.last_token
            pos[i] = r.seq_len
            kvl[i] = r.seq_len + 1
            mask[i, 0] = True
            limit[i] = r.max_len()
            temps[i] = r.sampling.temperature
            ks[i] = r.sampling.top_k
            table[i, :len(r.pages)] = r.pages
        return {
            "rows": list(batch), "B": B,
            "tok": jnp.asarray(tok), "pos": jnp.asarray(pos),
            "kvl": jnp.asarray(kvl), "mask": jnp.asarray(mask),
            "limit": jnp.asarray(limit),
            "temps": jnp.asarray(temps), "ks": jnp.asarray(ks),
            "table_np": table, "table": jnp.asarray(table),
            "pending": None,
        }

    def _decode_step(self) -> List[StepEvent]:
        events: List[StepEvent] = []
        batch = self._decode_batch()
        st = self._dec
        if st is not None and st["rows"] != batch:
            events.extend(self._drain_decode())
            st = None
            batch = self._decode_batch()
        if not batch:
            events.extend(self._drain_decode())
            return events

        # Ensure pages exist for the whole decode window; preempt the
        # youngest requests on exhaustion. Oldest-first so old requests
        # finish and release memory (deadlock-free under oversubscription).
        K = self.cfg.multi_step
        pages_changed = False
        for req in sorted(batch, key=lambda r: r.t_submit):
            if req.state != "running":
                continue  # preempted earlier in this very loop
            horizon = min(req.seq_len + K, req.max_len())
            need = pages_for_tokens(horizon, self.cfg.page_size) - len(req.pages)
            if need > 0:
                extra = self._alloc(need)
                while extra is None:
                    # Emit in-flight tokens before any pages are released:
                    # a preempted request must not receive a stale token
                    # (and an emitted finish may free enough on its own).
                    events.extend(self._drain_decode())
                    st = None
                    if req.state != "running":
                        break  # the drain just finished THIS request
                    extra = self._alloc(need)
                    if extra is not None:
                        break
                    victim = self._preempt_youngest(exclude=req)
                    if victim is None:
                        break
                    extra = self._alloc(need)
                if req.state != "running":
                    # Finished by a pending stop token emitted in the drain:
                    # its pages are already released — growing or preempting
                    # it now would leak pages / resurrect a finished stream.
                    if extra:
                        self.allocator.release(extra)
                    continue
                if extra is None:
                    events.extend(self._drain_decode())
                    st = None
                    if req.state != "running":
                        continue
                    self._preempt(req)
                    continue
                req.pages.extend(extra)
                pages_changed = True
        batch2 = self._decode_batch()
        if batch2 != batch:
            if st is not None:
                events.extend(self._drain_decode())
                st = None
            batch = batch2
        if not batch:
            return events

        if st is None:
            st = self._dec = self._build_decode_state(batch)
        elif pages_changed:
            for i, r in enumerate(batch):
                row = st["table_np"][i]
                row[:len(r.pages)] = r.pages
                row[len(r.pages):] = 0
            st["table"] = jnp.asarray(st["table_np"])

        fn = self._get_decode_fn(st["B"])
        toks_seq, tok, pos, kvl, kp, vp, ksc, vsc, self._dec_key = fn(
            self.params, st["tok"], st["pos"], st["kvl"], st["table"],
            st["mask"], st["limit"], self.cache.k_pages, self.cache.v_pages,
            self.cache.k_scales, self.cache.v_scales,
            self._dec_key, st["temps"], st["ks"])
        self.cache = PagedKVCache(k_pages=kp, v_pages=vp,
                                  k_scales=ksc, v_scales=vsc)
        st["tok"], st["pos"], st["kvl"] = tok, pos, kvl
        valid = []
        for req in batch:
            valid.append(min(K, req.max_len() - req.seq_len))
            req.seq_len = min(req.seq_len + K, req.max_len())

        prev, st["pending"] = st["pending"], (list(batch), toks_seq, valid)
        if prev is not None:
            events.extend(self._emit_pending(prev))
        return events

    def _emit(self, req: Request, tok: int) -> StepEvent:
        req.output.append(tok)
        req.last_token = tok
        finished = (
            len(req.output) >= req.sampling.max_new_tokens
            or (req.sampling.stop_token is not None and tok == req.sampling.stop_token)
        )
        if finished:
            self._finish(req)
        return StepEvent(req.id, tok, finished)

    # ---- lifecycle ----

    def _finish(self, req: Request):
        req.state = "finished"
        self.running = [r for r in self.running if r is not req]
        if self.cfg.mode == "prefill":
            # Disaggregated prefill: the pages ARE the product — the PD layer
            # exports them to a decode peer, then calls release_request().
            req.state = "exported"
            return
        if self.radix is not None:
            # Cache the full sequence (prompt + output) for future prefixes.
            self.radix.insert(req.prompt + req.output[:-1], req.pages)
        self.allocator.release(req.pages)
        req.pages = []
        # Don't retain finished requests forever (long-running servers).
        self.requests.pop(req.id, None)

    def release_request(self, req_id: int):
        """Release an exported request's pages (prefill mode)."""
        req = self.requests.pop(req_id)
        if req.pages:
            self.allocator.release(req.pages)
            req.pages = []

    def cancel_request(self, req_id: int) -> bool:
        """Abort a request: drop it from the queues and recycle its pages.
        (Must be called from the thread driving step() — the EngineService
        routes cancellations through its loop.)"""
        req = self.requests.get(req_id)
        if req is None or req.state == "finished":
            return False
        req.state = "finished"
        self.waiting = [r for r in self.waiting if r is not req]
        self.running = [r for r in self.running if r is not req]
        if req.pages:
            self.allocator.release(req.pages)
            req.pages = []
        self.requests.pop(req_id, None)
        return True

    def _preempt(self, req: Request):
        self.metrics["preemptions"] += 1
        self.allocator.release(req.pages)
        req.pages = []
        req.state = "waiting"
        req.prefill_pos = 0
        req.seq_len = 0
        req.shared_tokens = 0
        # Restart cleanly: generated tokens so far are kept as prompt
        # extension so decoding resumes where it left off.
        if req.output:
            req.prompt = req.prompt + req.output
            req.sampling = dataclasses.replace(
                req.sampling,
                max_new_tokens=req.sampling.max_new_tokens - len(req.output))
            req.output = []
        self.running = [r for r in self.running if r is not req]
        self.waiting.insert(0, req)

    def _preempt_youngest(self, exclude: Request) -> Optional[Request]:
        candidates = [r for r in self.running if r.state == "running" and r is not exclude]
        if not candidates:
            return None
        victim = max(candidates, key=lambda r: r.t_submit)
        self._preempt(victim)
        return victim

    # ---- device dispatch ----

    def _bucket(self, n: int) -> int:
        for b in self.cfg.decode_buckets:
            if b >= n:
                return min(b, max(self.cfg.decode_buckets))
        return max(self.cfg.decode_buckets)

    def _get_fwd(self, B: int, T: int):
        key = (B, T)
        fn = self._fwd_cache.get(key)
        if fn is None:
            import functools
            base = functools.partial(forward_paged, cfg=self.mcfg,
                                     use_pallas=self.cfg.use_pallas)

            def wrapped(params, tokens, positions, token_mask, kv_lens,
                        page_table, k_pages, v_pages, k_scales, v_scales):
                return base(params, tokens=tokens, positions=positions,
                            token_mask=token_mask, kv_lens=kv_lens,
                            page_table=page_table, k_pages=k_pages,
                            v_pages=v_pages, k_scales=k_scales,
                            v_scales=v_scales)

            donate = (6, 7, 8, 9) if self.cache.quantized else (6, 7)
            fn = jax.jit(wrapped, donate_argnums=donate)
            self._fwd_cache[key] = fn
        return fn

    def _run(self, tokens, positions, lens, pages, T_bucket, B_bucket=None):
        """Pad host-side lists to (B_bucket, T_bucket) and dispatch."""
        B = B_bucket or 1
        T = T_bucket
        P = self.cfg.max_pages_per_seq
        tok = np.zeros((B, T), np.int32)
        pos = np.zeros((B, T), np.int32)
        mask = np.zeros((B, T), bool)
        kvl = np.zeros((B,), np.int32)
        table = np.zeros((B, P), np.int32)
        for i, (ts, ps_, ln, pg) in enumerate(zip(tokens, positions, lens, pages)):
            tok[i, :len(ts)] = ts
            pos[i, :len(ps_)] = ps_
            mask[i, :len(ts)] = True
            kvl[i] = ln
            table[i, :len(pg)] = pg
        fn = self._get_fwd(B, T)
        logits, k_pages, v_pages, k_scales, v_scales = fn(
            self.params, jnp.asarray(tok), jnp.asarray(pos), jnp.asarray(mask),
            jnp.asarray(kvl), jnp.asarray(table),
            self.cache.k_pages, self.cache.v_pages,
            self.cache.k_scales, self.cache.v_scales,
        )
        self.cache = PagedKVCache(k_pages=k_pages, v_pages=v_pages,
                                  k_scales=k_scales, v_scales=v_scales)
        return logits  # device array; callers slice what they need
