"""Adaptive aggregation↔disaggregation topology subsystem.

Decision (``policy``) → actuation (``controller``) → proof
(``stress --scenario topoflip``). See docs/architecture.md §"Adaptive
topology".
"""

from rbg_tpu.topology.controller import (   # noqa: F401
    GroupTopology, TopologyConfig, TopologyController,
)
from rbg_tpu.topology.policy import (       # noqa: F401
    POSTURE_DISAGG, POSTURE_UNIFIED, REC_HOLD, TopologyDecision,
    TopologyPolicy, TopologyPolicyConfig, TopologySignals,
)
from rbg_tpu.topology.signals import (      # noqa: F401
    router_ingress_ratio, router_ingress_signals_fn,
)
