"""TopologyController — runtime aggregation↔disaggregation actuation.

A runtime controller (same lifecycle as the autoscaler,
``ControlPlane(topology=TopologyConfig(...))``) that lets a role group
flip between the unified shape (one engine role serving prefill+decode)
and the PD-disaggregated shape (prefill + decode roles over the PR-10
transfer plane) at runtime, with the router absorbing the transition
without dropping a stream.

The flip is a persistent per-group state machine carried ENTIRELY in
group annotations (``topology-state`` / ``topology-target`` /
``topology-posture``), so a plane restart resumes a mid-flight flip
exactly like the PR-3 migration machine resumes a slice move:

* **Warming** — the target shape's roles are scaled up through their
  ScalingAdapters (SparePool grants steer pending TPU instances onto
  reserved warm slices first); the machine waits for the target shape to
  report ready — capacity is made BEFORE anything is broken;
* **CutOver** — router candidacy flips role-by-role: the target roles
  become eligible for new traffic FIRST, then the old shape's roles are
  withdrawn (the serving set is published in the ``topology-serving``
  annotation and mirrored through ``candidacy_fn`` to live routers);
* **Draining** — the old shape's adapters go to 0 and the stateless
  instance engine walks every old instance through PreparingDelete:
  in-flight streams finish (or re-route token-exact via the PR-10
  bundle fallback) before the instance dies. The flip completes when no
  old-shape instance remains.

Actuator coordination: every adapter write stamps
``autoscale-last-write`` (the PR-9 two-writer protocol — whoever writes,
stamps), so the autoscaler adopts the new shape as its baseline instead
of fighting it; and a flip never STARTS while an adapter carries an
unadopted foreign write (``rbg_topology_conflicts_total`` + one-cycle
backoff), so the two actuators never interleave half-applied targets.
"""

from __future__ import annotations

import dataclasses
import json
import time
from typing import Callable, Dict, List, Optional, Tuple

from rbg_tpu.api import constants as C
from rbg_tpu.autoscale.signals import SignalReader
from rbg_tpu.obs import names
from rbg_tpu.obs import trace
from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.runtime.controller import Controller, Result, Watch
from rbg_tpu.runtime.store import (
    EVENT_WARNING, Conflict, NotFound, Store,
)
from rbg_tpu.topology.policy import (
    POSTURE_DISAGG, POSTURE_UNIFIED, REC_HOLD, TopologyDecision,
    TopologyPolicy, TopologyPolicyConfig, TopologySignals,
)
from rbg_tpu.utils.locktrace import named_lock

STATE_WARMING = "Warming"
STATE_CUTOVER = "CutOver"
STATE_DRAINING = "Draining"


@dataclasses.dataclass
class GroupTopology:
    """Shape plan for one group: which roles form each posture and the
    replica count each shape warms to. The group's spec carries ALL the
    roles; posture is which of them hold replicas + router candidacy."""

    group: str
    namespace: str = "default"
    unified_role: str = "unified"
    prefill_role: str = "prefill"
    decode_role: str = "decode"
    unified_replicas: int = 2
    prefill_replicas: int = 1
    decode_replicas: int = 1

    def shape_roles(self, posture: str) -> List[Tuple[str, int]]:
        if posture == POSTURE_UNIFIED:
            return [(self.unified_role, self.unified_replicas)]
        return [(self.prefill_role, self.prefill_replicas),
                (self.decode_role, self.decode_replicas)]

    def all_roles(self) -> List[str]:
        return [self.unified_role, self.prefill_role, self.decode_role]


@dataclasses.dataclass
class TopologyConfig:
    """Wiring for one plane's topology controller."""

    groups: List[GroupTopology] = dataclasses.field(default_factory=list)
    policy: TopologyPolicyConfig = dataclasses.field(
        default_factory=TopologyPolicyConfig)
    eval_period_s: float = 15.0
    window_s: float = 60.0
    stale_after_s: float = 10.0
    # Per-group decision-input overrides (GroupTopology -> dict with any
    # TopologySignals field): the seam the stress harness and router-fed
    # deployments use for signals the registry does not carry (the
    # ingress-vantage prompt:output token ratio above all).
    signals_fn: Optional[Callable[[GroupTopology], dict]] = None
    # Live-router candidacy mirror: (group, role, active) -> None, called
    # as the cutover phase flips roles. The annotation is the durable
    # record; this hook is the push path to in-process routers.
    candidacy_fn: Optional[Callable[[str, str, bool], None]] = None


class TopologyController(Controller):
    name = "topology"
    workers = 1

    def __init__(self, store: Store, config: TopologyConfig, spares=None):
        super().__init__(store)
        self.cfg = config
        self.spares = spares
        self.resync_period = max(config.eval_period_s, 0.05)
        # The resync IS the evaluation tick (autoscaler convention).
        self.backstop_period = self.resync_period
        self.flip_poll_s = min(self.resync_period, 0.25)
        self.reader = SignalReader(window_s=config.window_s,
                                   stale_after_s=config.stale_after_s)
        self._groups: Dict[tuple, GroupTopology] = {
            (g.namespace, g.group): g for g in config.groups}
        self._policies: Dict[tuple, TopologyPolicy] = {}
        # key -> {"root": span, "phase": span} for the flip in flight
        # (spans are process-local; a resumed flip starts fresh ones).
        self._spans: Dict[tuple, dict] = {}
        self._lock = named_lock("topology.status")
        # key -> status row  # guarded_by[topology.status]
        self._status: Dict[tuple, dict] = {}
        # runtime-disabled group names  # guarded_by[topology.status]
        self._disabled: set = set()

    # ---- wiring ----

    def watches(self) -> List[Watch]:
        def group_keys(obj):
            if obj.kind != "RoleBasedGroup":
                return []
            key = (obj.metadata.namespace, obj.metadata.name)
            return [key] if key in self._groups else []

        return [Watch("RoleBasedGroup", group_keys)]

    # ---- operator surface ----

    def set_enabled(self, group: str, enabled: bool,
                    namespace: Optional[str] = None) -> bool:
        """Runtime kill switch. ``namespace=None`` matches the group
        name in EVERY namespace it is configured in (the admin op's
        default); pass it to scope the flip. Returns True when anything
        matched."""
        keys = [(g.namespace, g.group) for g in self.cfg.groups
                if g.group == group
                and (namespace is None or g.namespace == namespace)]
        if not keys:
            return False
        with self._lock:
            for key in keys:
                if enabled:
                    self._disabled.discard(key)
                else:
                    self._disabled.add(key)
        return True

    def enabled(self, namespace: str, group: str) -> bool:
        with self._lock:
            return (namespace, group) not in self._disabled

    def status(self) -> dict:
        with self._lock:
            rows = [dict(v) for v in self._status.values()]
            disabled = set(self._disabled)
        for r in rows:
            # Live flag, not the last-reconcile snapshot: a kill-switch
            # flip must be visible in the op's own response.
            r["enabled"] = (r["namespace"], r["group"]) not in disabled
        rows.sort(key=lambda r: (r["namespace"], r["group"]))
        return {
            "eval_period_s": self.cfg.eval_period_s,
            "window_s": self.cfg.window_s,
            "groups": rows,
        }

    # ---- reconcile ----

    def reconcile(self, store: Store, key) -> Optional[Result]:
        gt = self._groups.get(tuple(key))
        if gt is None:
            return None
        ns, name = key
        rbg = store.get("RoleBasedGroup", ns, name, copy_=False)
        if rbg is None or rbg.metadata.deletion_timestamp is not None:
            return None
        ann = rbg.metadata.annotations
        posture = ann.get(C.ANN_TOPOLOGY_POSTURE) or self._infer(rbg, gt)
        state = ann.get(C.ANN_TOPOLOGY_STATE)
        now = time.monotonic()
        if state:
            self._gauge(name, 0.5)
            self._advance(store, gt, rbg, posture, state, now)
            return Result(requeue_after=self.flip_poll_s)

        self._gauge(name, 1.0 if posture == POSTURE_DISAGG else 0.0)
        policy = self._policy(key)
        if not self.enabled(ns, name):
            # Time spent disabled must never count as sustained pressure
            # at re-enable.
            policy.reset_pressure()
            d = TopologyDecision(posture, REC_HOLD, "disabled",
                                 suppressed="disabled")
            policy.last_decision = d
            self._record(gt, posture, None, d, now)
            return Result(requeue_after=self.cfg.eval_period_s)

        sig = self._signals(gt, now)
        d = policy.decide(now, sig, posture)
        if d.recommendation == REC_HOLD:
            REGISTRY.inc(names.TOPOLOGY_HOLDS_TOTAL, group=name,
                         reason=d.suppressed or "steady")
            if d.suppressed == "cost_gated":
                REGISTRY.inc(names.TOPOLOGY_COST_GATED_TOTAL, group=name)
        else:
            blocked = self._flip_blocked(store, gt, rbg, d)
            if blocked is not None:
                kind, why = blocked
                if kind == "conflict":
                    REGISTRY.inc(names.TOPOLOGY_CONFLICTS_TOTAL,
                                 group=name)
                REGISTRY.inc(names.TOPOLOGY_HOLDS_TOTAL, group=name,
                             reason=kind)
                policy.revoke(d)
                store.record_event(
                    rbg, "TopologyConflict" if kind == "conflict"
                    else "TopologyInfeasible",
                    f"flip to {d.recommendation} backed off: {why}",
                    type_=EVENT_WARNING)
                d = TopologyDecision(posture, REC_HOLD,
                                     f"{kind} (wanted "
                                     f"{d.recommendation}): {why}",
                                     suppressed=kind)
                policy.last_decision = d
            else:
                self._begin(store, gt, rbg, d)
        self._record(gt, posture, ann.get(C.ANN_TOPOLOGY_STATE), d, now)
        return Result(requeue_after=self.cfg.eval_period_s)

    # ---- decision inputs ----

    def _signals(self, gt: GroupTopology, now: float) -> TopologySignals:
        extras = {}
        if self.cfg.signals_fn is not None:
            try:
                extras = dict(self.cfg.signals_fn(gt) or {})
            except Exception:
                extras = {}
        fresh, age = self.reader.fresh()
        if extras.get("fresh") is not None:
            fresh = bool(extras["fresh"])
        ratio = extras.get("prefill_decode_ratio")
        if ratio is None:
            # Measured per-role token rates (meaningful once the group is
            # disaggregated; the reader reports None — never inf/0 — when
            # one side measured nothing in the window).
            ratio = self.reader.measured_ratio(gt.prefill_role,
                                               gt.decode_role)
        judged = extras.get("judged")
        ttft = extras.get("ttft_attainment")
        tpot = extras.get("tpot_attainment")
        good = extras.get("goodput_rps")
        if judged is None:
            judged, ttft_w, tpot_w, n_w = 0, 0.0, 0.0, 0
            for role in gt.all_roles():
                rs = self.reader.read(role)
                if not rs.judged:
                    continue
                judged += rs.judged
                if rs.ttft_attainment is not None:
                    ttft_w += rs.ttft_attainment * rs.judged
                if rs.tpot_attainment is not None:
                    tpot_w += rs.tpot_attainment * rs.judged
                n_w += rs.judged
                if rs.goodput_rps is not None:
                    good = (good or 0.0) + rs.goodput_rps
            if n_w and ttft is None:
                ttft = round(ttft_w / n_w, 4)
            if n_w and tpot is None:
                tpot = round(tpot_w / n_w, 4)
        link = extras.get("link_bytes_per_s")
        if link is None:
            link = self._measured_link_rate()
        return TopologySignals(
            fresh=fresh, sample_age_s=age,
            prefill_decode_ratio=ratio, judged=int(judged or 0),
            ttft_attainment=ttft, tpot_attainment=tpot, goodput_rps=good,
            queue_depth=extras.get("queue_depth"),
            kv_bytes_to_move=extras.get("kv_bytes_to_move"),
            link_bytes_per_s=link)

    @staticmethod
    def _measured_link_rate() -> Optional[float]:
        """Fastest measured KV link (``rbg_kvtransfer_link_bytes_per_s``)
        — the rate a warm flip would actually move pages at."""
        _, gauges, _ = REGISTRY.snapshot_values()
        rates = [v for k, v in gauges.items()
                 if k[0] == names.KVT_LINK_RATE]
        return max(rates) if rates else None

    # ---- flip state machine ----

    def _infer(self, rbg, gt: GroupTopology) -> str:
        u = rbg.spec.role(gt.unified_role)
        return POSTURE_UNIFIED if (u is not None and u.replicas > 0) \
            else POSTURE_DISAGG

    def _policy(self, key) -> TopologyPolicy:
        key = tuple(key)
        p = self._policies.get(key)
        if p is None:
            p = self._policies[key] = TopologyPolicy(self.cfg.policy)
        return p

    def _gauge(self, group: str, value: float) -> None:
        REGISTRY.set_gauge(names.TOPOLOGY_POSTURE, value, group=group)

    def _adapters(self, store, gt: GroupTopology, rbg) -> Dict[str, object]:
        roles = set(gt.all_roles())
        return {sa.spec.role_name: sa
                for sa in store.list_for("ScalingAdapter", rbg, copy_=False)
                if sa.spec.role_name in roles}

    def _flip_blocked(self, store, gt, rbg, d) -> Optional[tuple]:
        """(kind, why) when this flip must not START, else None.

        ``conflict``: an adapter carries a write the stamping writer has
        not adopted yet — flipping now would interleave two actuators'
        half-applied targets. ``infeasible``: the adapters' own [min,
        max] bounds make the flip un-completable (an old-shape role with
        min_replicas > 0 can never drain to zero; a target role with
        max_replicas below its plan can never report ready) — refusing
        up front turns a would-be permanent mid-flip wedge into a
        visible, retriable HOLD."""
        adapters = self._adapters(store, gt, rbg)
        for sa in adapters.values():
            stamp = sa.metadata.annotations.get(C.ANN_AUTOSCALE_LAST_WRITE)
            if (stamp is not None and sa.spec.replicas is not None
                    and str(sa.spec.replicas) != stamp):
                return ("conflict", "another actuator's adapter write "
                                    "is in flight")
        target = d.recommendation
        new_roles = {r for r, _ in gt.shape_roles(target)}
        for role, plan in gt.shape_roles(target):
            sa = adapters.get(role)
            if (sa is not None and sa.spec.max_replicas > 0
                    and sa.spec.max_replicas < plan):
                return ("infeasible",
                        f"{role} adapter max_replicas="
                        f"{sa.spec.max_replicas} < shape plan {plan}")
        for role, _ in gt.shape_roles(d.current):
            sa = adapters.get(role)
            if (role not in new_roles and sa is not None
                    and sa.spec.min_replicas > 0):
                return ("infeasible",
                        f"{role} adapter min_replicas="
                        f"{sa.spec.min_replicas} > 0: old shape can "
                        f"never drain")
        return None

    def _begin(self, store, gt: GroupTopology, rbg,
               d: TopologyDecision) -> None:
        ns, name = gt.namespace, gt.group
        target = d.recommendation
        started = f"{time.time():.3f}"

        def fn(g):
            a = g.metadata.annotations
            if a.get(C.ANN_TOPOLOGY_STATE):
                return False     # a concurrent pass already started one
            a[C.ANN_TOPOLOGY_STATE] = STATE_WARMING
            a[C.ANN_TOPOLOGY_TARGET] = target
            a[C.ANN_TOPOLOGY_STARTED] = started
            a.setdefault(C.ANN_TOPOLOGY_POSTURE, d.current)
            return True

        try:
            store.mutate("RoleBasedGroup", ns, name, fn)
        except (NotFound, Conflict):
            self._policy((ns, name)).revoke(d)
            return
        root = trace.start_trace(names.SPAN_TOPOLOGY_FLIP, group=name,
                                 target=target)
        self._spans[(ns, name)] = {
            "root": root,
            "phase": root.child(names.SPAN_TOPOLOGY_WARM)}
        self._gauge(name, 0.5)
        store.record_event(
            rbg, "TopologyFlip",
            f"{d.current} -> {target} ({d.reason}); warming "
            f"{[r for r, _ in gt.shape_roles(target)]}")

    def _advance(self, store, gt: GroupTopology, rbg, posture: str,
                 state: str, now: float) -> None:
        ns, name = gt.namespace, gt.group
        ann = rbg.metadata.annotations
        target = ann.get(C.ANN_TOPOLOGY_TARGET) or posture
        if state == STATE_WARMING:
            self._ensure_shape(store, gt, rbg, gt.shape_roles(target))
            if self._shape_ready(store, gt, rbg, target):
                self._set_state(store, gt, STATE_CUTOVER,
                                names.SPAN_TOPOLOGY_CUTOVER)
        elif state == STATE_CUTOVER:
            self._cutover(store, gt, rbg, posture, target)
            self._set_state(store, gt, STATE_DRAINING,
                            names.SPAN_TOPOLOGY_DRAIN)
        elif state == STATE_DRAINING:
            old = gt.shape_roles(posture)
            self._ensure_shape(store, gt, rbg,
                               [(r, 0) for r, _ in old])
            if self._drained(store, gt, [r for r, _ in old]):
                self._complete(store, gt, rbg, posture, target, now)
        self._record(gt, posture, state, None, now, target=target)

    def _set_state(self, store, gt: GroupTopology, state: str,
                   span_name: str) -> None:
        ns, name = gt.namespace, gt.group

        def fn(g):
            a = g.metadata.annotations
            if a.get(C.ANN_TOPOLOGY_STATE) == state:
                return False
            a[C.ANN_TOPOLOGY_STATE] = state
            return True

        try:
            store.mutate("RoleBasedGroup", ns, name, fn)
        except (NotFound, Conflict):
            return
        spans = self._spans.get((ns, name))
        if spans is not None:
            spans["phase"].end()
            spans["phase"] = spans["root"].child(span_name)

    def _ensure_shape(self, store, gt: GroupTopology, rbg,
                      roles: List[Tuple[str, int]]) -> None:
        """Idempotent adapter writes for a shape's roles, each stamped
        with the two-writer ownership annotation; pending TPU instances
        of a warming role get SparePool grants."""
        from rbg_tpu.autoscale.controller import AutoscaleController
        from rbg_tpu.runtime.controllers.scalingadapter import adapter_name
        ns = gt.namespace
        for role, replicas in roles:
            sa_name = adapter_name(gt.group, role)

            def fn(a, replicas=replicas):
                # The adapter's own [min, max] bounds the write (the
                # PR-9 clamp, applied on OUR side so the adapter
                # controller never rewrites our value — which would
                # read as a foreign writer next cycle). _flip_blocked
                # already refused flips these bounds make
                # un-completable.
                v = AutoscaleController._bound_to_adapter(a, replicas)
                if (a.spec.replicas == v
                        and a.metadata.annotations.get(
                            C.ANN_AUTOSCALE_LAST_WRITE) == str(v)):
                    return False
                a.spec.replicas = v
                # Whoever writes, stamps (PR-9 protocol): the autoscaler
                # adopts this as its baseline instead of conflicting.
                a.metadata.annotations[C.ANN_AUTOSCALE_LAST_WRITE] = str(v)
                return True

            try:
                store.mutate("ScalingAdapter", ns, sa_name, fn)
            except (NotFound, Conflict):
                continue     # adapter not created yet — next poll retries
            if replicas > 0:
                self._grant_spares(store, gt, rbg, role)

    def _grant_spares(self, store, gt: GroupTopology, rbg, role) -> None:
        """Bind-time warm-up: unbound pending TPU instances of a warming
        role take reserved spare slices (the PR-3 grant seam, shared
        with the autoscaler via ``capacity.grant_spares_for_role``)."""
        from rbg_tpu.sched.capacity import grant_spares_for_role
        spec = rbg.spec.role(role)
        if self.spares is None or spec is None or spec.tpu is None:
            return

        def on_grant(inst, target):
            store.record_event(
                inst, "TopologySpareGrant",
                f"warming {role} granted warm spare {target}")

        grant_spares_for_role(store, self.spares, gt.namespace, gt.group,
                              role, spec.tpu.slice_topology,
                              on_grant=on_grant)

    def _shape_ready(self, store, gt: GroupTopology, rbg,
                     target: str) -> bool:
        """Every target role reports ready at the replica count the
        adapter write could actually LAND (the clamped value — bounds
        may have tightened mid-flip; comparing against the unclamped
        plan would park the machine in Warming forever)."""
        from rbg_tpu.autoscale.controller import AutoscaleController
        adapters = self._adapters(store, gt, rbg)
        for role, replicas in gt.shape_roles(target):
            sa = adapters.get(role)
            want = (AutoscaleController._bound_to_adapter(sa, replicas)
                    if sa is not None else replicas)
            st = rbg.status.role(role)
            if st is None or st.ready_replicas < want:
                return False
        return True

    def _cutover(self, store, gt: GroupTopology, rbg, posture: str,
                 target: str) -> None:
        """Role-by-role candidacy flip: the target shape's roles join the
        serving set FIRST, then the old shape's roles are withdrawn —
        there is never an instant with no candidate for new traffic."""
        ns, name = gt.namespace, gt.group
        new_roles = [r for r, _ in gt.shape_roles(target)]
        old_roles = [r for r, _ in gt.shape_roles(posture)
                     if r not in new_roles]
        for role in new_roles:
            self._set_candidacy(name, role, True)
        self._publish_serving(store, gt, new_roles + old_roles)
        for role in old_roles:
            self._set_candidacy(name, role, False)
        self._publish_serving(store, gt, new_roles)
        store.record_event(
            rbg, "TopologyCutOver",
            f"router candidacy -> {new_roles} (withdrawn: {old_roles})")

    def _set_candidacy(self, group: str, role: str, active: bool) -> None:
        if self.cfg.candidacy_fn is None:
            return
        try:
            self.cfg.candidacy_fn(group, role, active)
        except Exception:
            pass

    def _publish_serving(self, store, gt: GroupTopology,
                         roles: List[str]) -> None:
        val = json.dumps(sorted(roles))

        def fn(g):
            if g.metadata.annotations.get(C.ANN_TOPOLOGY_SERVING) == val:
                return False
            g.metadata.annotations[C.ANN_TOPOLOGY_SERVING] = val
            return True

        try:
            store.mutate("RoleBasedGroup", gt.namespace, gt.group, fn)
        except (NotFound, Conflict):
            pass

    def _drained(self, store, gt: GroupTopology,
                 old_roles: List[str]) -> bool:
        """The old shape is gone only when no RoleInstance of its roles
        survives — every drain window ran to ack or deadline, so every
        in-flight stream finished or re-routed."""
        for role in old_roles:
            if store.list("RoleInstance", namespace=gt.namespace,
                          selector={C.LABEL_GROUP_NAME: gt.group,
                                    C.LABEL_ROLE_NAME: role},
                          copy_=False):
                return False
        return True

    def _complete(self, store, gt: GroupTopology, rbg, posture: str,
                  target: str, now: float) -> None:
        ns, name = gt.namespace, gt.group
        started = rbg.metadata.annotations.get(C.ANN_TOPOLOGY_STARTED)

        def fn(g):
            a = g.metadata.annotations
            if not a.get(C.ANN_TOPOLOGY_STATE):
                return False
            a.pop(C.ANN_TOPOLOGY_STATE, None)
            a.pop(C.ANN_TOPOLOGY_TARGET, None)
            a.pop(C.ANN_TOPOLOGY_STARTED, None)
            a[C.ANN_TOPOLOGY_POSTURE] = target
            return True

        try:
            store.mutate("RoleBasedGroup", ns, name, fn)
        except (NotFound, Conflict):
            return
        try:
            duration = max(0.0, time.time() - float(started))
        except (TypeError, ValueError):
            duration = 0.0
        REGISTRY.observe(names.TOPOLOGY_SWITCH_DURATION_SECONDS, duration,
                         target=target)
        REGISTRY.inc(names.TOPOLOGY_FLIPS_TOTAL, group=name, target=target)
        self._gauge(name, 1.0 if target == POSTURE_DISAGG else 0.0)
        # Cooldown re-latches at completion too, so a plane that RESUMED
        # this flip from annotations (decide() never ran here) still
        # honors the post-flip cooldown.
        self._policy((ns, name)).note_flip(now)
        spans = self._spans.pop((ns, name), None)
        if spans is not None:
            spans["phase"].end()
            spans["root"].end(outcome="flipped", duration_s=round(duration, 3))
        store.record_event(
            rbg, "TopologyFlipped",
            f"{posture} -> {target} in {duration:.2f}s (old shape drained)")

    # ---- bookkeeping ----

    def _record(self, gt: GroupTopology, posture: str,
                state: Optional[str], decision: Optional[TopologyDecision],
                now: float, target: Optional[str] = None) -> None:
        key = (gt.namespace, gt.group)
        policy = self._policy(key)
        row = {
            "namespace": gt.namespace, "group": gt.group,
            "posture": posture, "state": state or "",
            "target": target or "",
            "enabled": self.enabled(gt.namespace, gt.group),
            "cooldown_remaining_s": round(
                policy.cooldown_remaining(now), 2),
            "last_decision": (decision.as_dict() if decision is not None
                              else (policy.last_decision.as_dict()
                                    if policy.last_decision else None)),
        }
        with self._lock:
            self._status[key] = row
