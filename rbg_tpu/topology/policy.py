"""Topology decision layer: UNIFIED vs DISAGG vs HOLD.

Per "Prefill-Decode Aggregation or Disaggregation? Unifying Both for
Goodput-Optimized LLM Serving" (PAPERS.md): neither PD shape wins at every
load mix — long-prompt traffic wants disaggregation (prefill never
monopolizes decode steps), short-prompt chat wants the unified engine (no
KV transfer tax). The policy reads the measured prefill:decode token
ratio from the windowed-signal plane and recommends a shape behind the
same stability machinery the autoscaler uses (PR-9 policy style):

* **deadband hysteresis** — DISAGG pressure only at ratio >=
  ``disagg_ratio``, UNIFIED pressure only at ratio <= ``unified_ratio``;
  the band between is a deliberate no-man's-land so a mix oscillating
  around one threshold cannot flap the fleet;
* **direction-split stabilization** — pressure toward a shape must hold
  continuously for that direction's stabilization window before it
  actuates (disagg and unified windows tune independently);
* **cooldown** — after a flip starts, the group holds for ``cooldown_s``;
* **staleness / missing ratio → HOLD** — a dead sampler or a ratio the
  reader could not measure (one PD side judged nothing in the window)
  never drives a flip, and pressure onsets are forgotten;
* **switch-cost gate** — the estimated KV bytes to re-home over the
  MEASURED link rate (``rbg_kvtransfer_link_bytes_per_s``) must fit
  ``max_switch_cost_s``, or the flip is vetoed: a shape change that costs
  more than it buys is thrash, not optimization.

Pure state-machine code: ``now`` is a parameter, no clocks are read, no
store is touched — the controller owns all I/O.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

POSTURE_UNIFIED = "unified"
POSTURE_DISAGG = "disagg"
POSTURES = (POSTURE_UNIFIED, POSTURE_DISAGG)
REC_HOLD = "hold"


@dataclasses.dataclass(frozen=True)
class TopologySignals:
    """One group's windowed decision inputs at one evaluation instant.
    ``None`` fields mean "not measured in this window"."""

    fresh: bool
    sample_age_s: Optional[float] = None
    # Prompt:output token-rate ratio over the window (ingress vantage, or
    # per-role token rates when the group is already disaggregated).
    prefill_decode_ratio: Optional[float] = None
    judged: int = 0
    ttft_attainment: Optional[float] = None
    tpot_attainment: Optional[float] = None
    goodput_rps: Optional[float] = None
    queue_depth: Optional[float] = None
    # Switch-cost inputs: resident KV the flip would re-home, and the
    # measured transfer-plane link rate.
    kv_bytes_to_move: Optional[float] = None
    link_bytes_per_s: Optional[float] = None

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class TopologyPolicyConfig:
    """Tuning for one group's shape decision. The deadband is
    [unified_ratio, disagg_ratio]; keep it wide — the cost of a wrong
    HOLD is a few percent of goodput, the cost of a flap is a full warm +
    drain cycle."""

    disagg_ratio: float = 6.0      # ratio >= this -> DISAGG pressure
    unified_ratio: float = 2.0     # ratio <= this -> UNIFIED pressure
    min_judged: int = 3            # below this the window is anecdote
    disagg_stabilization_s: float = 30.0
    unified_stabilization_s: float = 60.0
    cooldown_s: float = 120.0
    # Flip veto: estimated KV move time (bytes / measured link rate) must
    # stay under this. 0 disables the gate.
    max_switch_cost_s: float = 30.0
    enabled: bool = True


@dataclasses.dataclass
class TopologyDecision:
    current: str                   # posture the decision was made from
    recommendation: str            # unified | disagg | hold
    reason: str
    # stale | no_ratio | low_sample | deadband | stabilizing | cooldown |
    # cost_gated | disabled
    suppressed: Optional[str] = None
    est_switch_cost_s: Optional[float] = None

    def as_dict(self) -> dict:
        return dataclasses.asdict(self)


class TopologyPolicy:
    """Hysteresis state for one group. ``decide(now, signals, current)``
    is the whole API; the instance remembers per-direction pressure
    onsets and the last flip time. Like the autoscaler's RoleScaler, a
    non-HOLD decision latches cooldown immediately — ``revoke()`` gives
    it back when the controller could not start the flip (actuator
    conflict), so a retry is not charged a cooldown for a flip that never
    happened."""

    def __init__(self, cfg: TopologyPolicyConfig):
        self.cfg = cfg
        self._pressure_since: Optional[float] = None
        self._pressure_target: Optional[str] = None
        self._last_flip: Optional[float] = None
        self._revoke_state: Optional[tuple] = None
        self.last_decision: Optional[TopologyDecision] = None

    # -- internals --

    def _hold(self, current: str, reason: str,
              suppressed: Optional[str] = None,
              est_cost: Optional[float] = None) -> TopologyDecision:
        d = TopologyDecision(current, REC_HOLD, reason,
                             suppressed=suppressed,
                             est_switch_cost_s=est_cost)
        self.last_decision = d
        return d

    def _forget_pressure(self) -> None:
        self._pressure_since = None
        self._pressure_target = None

    @staticmethod
    def estimate_cost_s(sig: TopologySignals) -> Optional[float]:
        """KV move time the flip would spend, from measured inputs; None
        when either side is unmeasured (no transfers yet — an unknown
        cost must not block the first flip forever)."""
        if not sig.kv_bytes_to_move or not sig.link_bytes_per_s:
            return None
        if sig.link_bytes_per_s <= 0:
            return None
        return sig.kv_bytes_to_move / sig.link_bytes_per_s

    # -- the API --

    def decide(self, now: float, sig: TopologySignals,
               current: str) -> TopologyDecision:
        cfg = self.cfg
        if not cfg.enabled:
            return self._hold(current, "disabled", suppressed="disabled")
        if not sig.fresh:
            # A dead scrape never flips a fleet; stale time is not
            # evidence of a sustained mix either.
            self._forget_pressure()
            return self._hold(current, "signals stale", suppressed="stale")
        ratio = sig.prefill_decode_ratio
        if ratio is None:
            # The reader refused to fabricate a ratio (one PD side judged
            # nothing in the window) — HOLD, never flip on inf/0.
            self._forget_pressure()
            return self._hold(current, "prefill:decode ratio unmeasured",
                              suppressed="no_ratio")
        if sig.judged < cfg.min_judged:
            self._forget_pressure()
            return self._hold(
                current, f"only {sig.judged} judged < {cfg.min_judged}",
                suppressed="low_sample")

        if ratio >= cfg.disagg_ratio:
            target = POSTURE_DISAGG
            why = f"ratio {ratio:.2f} >= {cfg.disagg_ratio:.2f}"
            window = cfg.disagg_stabilization_s
        elif ratio <= cfg.unified_ratio:
            target = POSTURE_UNIFIED
            why = f"ratio {ratio:.2f} <= {cfg.unified_ratio:.2f}"
            window = cfg.unified_stabilization_s
        else:
            self._forget_pressure()
            return self._hold(
                current,
                f"ratio {ratio:.2f} inside deadband "
                f"[{cfg.unified_ratio:.2f}, {cfg.disagg_ratio:.2f}]",
                suppressed="deadband")

        if target == current:
            self._forget_pressure()
            return self._hold(current, f"already {current} ({why})")

        # Direction-split stabilization: the onset restarts whenever the
        # pressure direction changes.
        if self._pressure_target != target:
            self._pressure_target = target
            self._pressure_since = now
        if now - self._pressure_since < window:
            return self._hold(current, f"{why} (stabilizing toward {target})",
                              suppressed="stabilizing")

        est_cost = self.estimate_cost_s(sig)
        if (cfg.max_switch_cost_s > 0 and est_cost is not None
                and est_cost > cfg.max_switch_cost_s):
            return self._hold(
                current,
                f"{why} but KV move ~{est_cost:.1f}s > "
                f"{cfg.max_switch_cost_s:.1f}s gate",
                suppressed="cost_gated", est_cost=est_cost)

        if (self._last_flip is not None
                and now - self._last_flip < cfg.cooldown_s):
            return self._hold(current, f"cooldown ({why})",
                              suppressed="cooldown", est_cost=est_cost)

        self._revoke_state = (self._last_flip, self._pressure_since,
                              self._pressure_target)
        self._last_flip = now
        self._forget_pressure()
        d = TopologyDecision(current, target, why,
                             est_switch_cost_s=est_cost)
        self.last_decision = d
        return d

    def revoke(self, decision: TopologyDecision) -> None:
        """The controller could not START this flip (another actuator's
        write was in flight, target write lost): undo the cooldown latch
        and restore the pressure onset."""
        if decision is not self.last_decision \
                or decision.recommendation == REC_HOLD:
            return
        if self._revoke_state is not None:
            (self._last_flip, self._pressure_since,
             self._pressure_target) = self._revoke_state
            self._revoke_state = None

    def reset_pressure(self) -> None:
        """Forget the pressure onset without touching cooldown — called
        while the group is runtime-disabled, so time spent disabled can
        never count as sustained pressure at re-enable."""
        self._forget_pressure()

    def note_flip(self, now: float) -> None:
        """Re-latch cooldown at flip COMPLETION (also called by a plane
        that resumed a mid-flight flip from annotations, where decide()
        never ran in this process)."""
        self._last_flip = now
        self._forget_pressure()

    def cooldown_remaining(self, now: float) -> float:
        if self._last_flip is None:
            return 0.0
        return max(0.0, self.cfg.cooldown_s - (now - self._last_flip))
