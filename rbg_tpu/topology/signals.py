"""Production ratio signal for the topology policy — router-ingress fed.

The PR-13 controller left a seam: ``TopologyConfig.signals_fn`` was only
exercised by the stress drill's scripted inputs, while production planes
fell back to per-role token rates that are only measurable once a group
is ALREADY disaggregated. The router now publishes
``rbg_router_ingress_tokens_total{kind="prefill"|"decode"}`` — prompt
tokens at dispatch, output tokens at delivery, the true ingress-vantage
load mix — and this module turns the windowed rate of that counter pair
into the ``prefill_decode_ratio`` the policy steers on.

Absence-of-signal discipline matches ``SignalReader.measured_ratio``: a
side that measured nothing in the window yields NO ratio (empty extras),
never 0 or ∞ — the controller then falls back to per-role rates or
HOLDs.
"""

from __future__ import annotations

from typing import Optional

from rbg_tpu.obs import names


def router_ingress_signals_fn(sampler=None, window_s: float = 60.0):
    """Build a ``TopologyConfig.signals_fn`` reading the router-ingress
    token counters through the windowed sampler (the PR-8 plane). Wire it
    in any plane whose router shares the process's metrics registry:

        TopologyConfig(groups=[...],
                       signals_fn=router_ingress_signals_fn())
    """
    if sampler is None:
        from rbg_tpu.obs import timeseries
        sampler = timeseries.get_sampler()

    def signals_fn(_gt) -> dict:
        ratio = router_ingress_ratio(sampler, window_s)
        return {} if ratio is None else {"prefill_decode_ratio": ratio}

    return signals_fn


def router_ingress_ratio(sampler, window_s: float = 60.0,
                         now: Optional[float] = None) -> Optional[float]:
    """Windowed prefill:decode token-rate ratio at router ingress, or
    None when either side measured no activity (absence of signal, not a
    measurement of 0/∞)."""
    num = sampler.rate(names.ROUTER_INGRESS_TOKENS_TOTAL, window_s,
                       now=now, kind="prefill")
    den = sampler.rate(names.ROUTER_INGRESS_TOKENS_TOTAL, window_s,
                       now=now, kind="decode")
    if num is None or den is None or num <= 1e-9 or den <= 1e-9:
        return None
    return num / den


# ---- router TIER aggregation (engine/routertier.py) ----
#
# The aggregation contract: with N routers each serving 1/N of the
# traffic, any single router's counter pair is a biased shard of the
# load mix (sessions hash by prefix, so one router can be all-prefill
# while another is all-decode). The policy input must therefore be the
# ratio of SUMS across members — never the mean of per-member ratios —
# and the result is identical whether the same trace flows through 1
# router or N (the identity `stress --scenario ha` asserts).


def tier_ingress_signals_fn(tier, window_s: float = 60.0):
    """Build a ``TopologyConfig.signals_fn`` reading the CROSS-ROUTER
    ingress aggregate from a :class:`~rbg_tpu.engine.routertier.RouterTier`
    — the N-router replacement for ``router_ingress_signals_fn`` (whose
    process-local sampler only ever sees one member's shard)."""

    def signals_fn(_gt) -> dict:
        ratio = tier_ingress_ratio(tier, window_s)
        return {} if ratio is None else {"prefill_decode_ratio": ratio}

    return signals_fn


def tier_ingress_ratio(tier, window_s: float = 60.0,
                       now: Optional[float] = None) -> Optional[float]:
    """Windowed prefill:decode ratio over token rates SUMMED across every
    tier member. Same absence-of-signal discipline as the single-router
    reader: a side with no samples in the window yields None."""
    rates = tier.ingress_rates(window_s, now=now)
    num, den = rates.get("prefill"), rates.get("decode")
    if num is None or den is None or num <= 1e-9 or den <= 1e-9:
        return None
    return num / den
