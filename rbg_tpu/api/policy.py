"""Policy / adapter / warmup / profile resources.

Reference analogs: ``coordinatedpolicy_types.go:24-152`` (inventory #22),
``rolebasedgroupscalingadapter_types.go`` (#8),
``rolebasedgroupwarmup_types.go:34-249`` (#9),
``clusterengineruntimeprofile_types.go`` (#19).
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional

from rbg_tpu.api.meta import Condition, ObjectMeta
from rbg_tpu.api.pod import Container, PodTemplate


class ProgressionGate(str, enum.Enum):
    ORDER_SCHEDULED = "OrderScheduled"
    ORDER_READY = "OrderReady"


@dataclasses.dataclass
class CoordinatedScaling:
    """maxSkew-bounded multi-role scaling: roles in ``roles`` scale together,
    never diverging more than maxSkew percent in progress."""

    roles: List[str] = dataclasses.field(default_factory=list)
    max_skew_percent: int = 10
    gate: ProgressionGate = ProgressionGate.ORDER_READY


@dataclasses.dataclass
class CoordinatedRollingUpdate:
    roles: List[str] = dataclasses.field(default_factory=list)
    max_skew_percent: int = 10


@dataclasses.dataclass
class CoordinatedPolicySpec:
    group_name: str = ""
    scaling: Optional[CoordinatedScaling] = None
    rolling_update: Optional[CoordinatedRollingUpdate] = None


@dataclasses.dataclass
class CoordinatedPolicy:
    kind: str = "CoordinatedPolicy"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: CoordinatedPolicySpec = dataclasses.field(default_factory=CoordinatedPolicySpec)

    __serde_keep__ = ("kind", "metadata")


@dataclasses.dataclass
class ScalingAdapterSpec:
    """HPA bridge: an external autoscaler drives ``replicas`` here; the
    controller writes it through to the target role."""

    group_name: str = ""
    role_name: str = ""
    replicas: Optional[int] = None
    min_replicas: int = 0
    max_replicas: int = 0


@dataclasses.dataclass
class ScalingAdapterStatus:
    phase: str = "NotBound"     # Bound | NotBound
    replicas: int = 0
    selector: str = ""

    __serde_keep__ = ("phase",)


@dataclasses.dataclass
class ScalingAdapter:
    kind: str = "ScalingAdapter"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: ScalingAdapterSpec = dataclasses.field(default_factory=ScalingAdapterSpec)
    status: ScalingAdapterStatus = dataclasses.field(default_factory=ScalingAdapterStatus)

    __serde_keep__ = ("kind", "metadata")


@dataclasses.dataclass
class ImagePreload:
    """Pull these images onto the node ahead of time (reference:
    ``ImagePreloadAction``, ``rolebasedgroupwarmup_types.go:34-45``)."""

    images: List[str] = dataclasses.field(default_factory=list)
    pull_secrets: List[str] = dataclasses.field(default_factory=list)


@dataclasses.dataclass
class WarmupActions:
    """What to run on each target node: per-image pull containers and/or
    user containers (reference: ``WarmupActions`` / ``CustomizedAction``,
    types ``:47-75``; container construction ``buildWarmupPod:535``)."""

    image_preload: Optional[ImagePreload] = None
    containers: List[Container] = dataclasses.field(default_factory=list)
    volumes: List[str] = dataclasses.field(default_factory=list)

    @property
    def empty(self) -> bool:
        return (self.image_preload is None and not self.containers
                and not self.volumes)


@dataclasses.dataclass
class WarmupTarget:
    nodes: List[str] = dataclasses.field(default_factory=list)  # explicit
    # Or: nodes selected by labels (reference TargetNodes.NodeSelector).
    node_selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    group_name: str = ""        # or: nodes discovered from a group's pods
    # With group_name: per-ROLE actions — each node gets the union of the
    # actions of the roles whose pods it hosts (reference
    # TargetRoleBasedGroup.Roles, types ``:96-110``). Empty = spec.actions
    # on every node of the group.
    roles: Dict[str, WarmupActions] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class WarmupSpec:
    """Node warmup jobs: image preload / cache priming per node before a
    group lands (reference: #9). On TPU the canonical use is XLA compile-cache
    priming and model-weight prefetch to hosts of the target slice."""

    target: WarmupTarget = dataclasses.field(default_factory=WarmupTarget)
    # Actions for node-targeted warmups (and the group default when
    # target.roles is empty).
    actions: WarmupActions = dataclasses.field(default_factory=WarmupActions)
    # Legacy single-template form (pre-actions API): used verbatim when no
    # actions are given anywhere.
    template: PodTemplate = dataclasses.field(default_factory=PodTemplate)
    parallelism: int = 4
    max_failed_nodes: int = 0
    backoff_limit: int = 3
    timeout_seconds: float = 600.0
    ttl_seconds_after_finished: float = 300.0


@dataclasses.dataclass
class WarmupStatus:
    phase: str = "Pending"      # Pending | Running | Succeeded | Failed
    desired_nodes: int = 0
    succeeded_nodes: int = 0
    failed_nodes: int = 0
    conditions: List[Condition] = dataclasses.field(default_factory=list)
    completion_time: float = 0.0

    __serde_keep__ = ("phase",)


@dataclasses.dataclass
class Warmup:
    kind: str = "Warmup"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: WarmupSpec = dataclasses.field(default_factory=WarmupSpec)
    status: WarmupStatus = dataclasses.field(default_factory=WarmupStatus)

    __serde_keep__ = ("kind", "metadata")


@dataclasses.dataclass
class PodGroupSpec:
    """Gang scheduling: all-or-nothing placement of min_member pods.

    Reference analog: ``pkg/scheduler/podgroup_manager.go:64-78`` (PodGroup CR
    for scheduler-plugins / Volcano, MinMember = total pods in group,
    ``helper.go:69-85``). On TPU, the gang is the slice: a multi-host role
    instance must acquire all hosts of one ICI domain atomically or none.
    """

    min_member: int = 1
    group_name: str = ""        # owning RoleBasedGroup
    queue: str = ""
    priority: int = 0


@dataclasses.dataclass
class PodGroupStatus:
    phase: str = "Pending"      # Pending | Scheduled
    scheduled: int = 0

    __serde_keep__ = ("phase",)


@dataclasses.dataclass
class PodGroup:
    kind: str = "PodGroup"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: PodGroupSpec = dataclasses.field(default_factory=PodGroupSpec)
    status: PodGroupStatus = dataclasses.field(default_factory=PodGroupStatus)

    __serde_keep__ = ("kind", "metadata")


@dataclasses.dataclass
class EngineRuntimeProfile:
    """Cluster-scoped bundle of sidecar/init containers + volumes injected
    into role pods (reference: #19, ``sidecar_builder.go:47-158``)."""

    kind: str = "EngineRuntimeProfile"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    init_containers: List[Container] = dataclasses.field(default_factory=list)
    containers: List[Container] = dataclasses.field(default_factory=list)
    volumes: List[str] = dataclasses.field(default_factory=list)

    __serde_keep__ = ("kind", "metadata")
