"""Resource model (the CRD-equivalent API surface). See SURVEY.md §2 #1-3."""

from rbg_tpu.api import constants
from rbg_tpu.api.group import (
    ComponentSpec, EngineRuntimeRef, GroupTemplate, LeaderWorkerSpec,
    PatternType, RestartPolicy, RestartPolicyConfig, RoleBasedGroup,
    RoleBasedGroupSet, RoleBasedGroupSetSpec, RoleBasedGroupSpec,
    RoleBasedGroupStatus, RoleSpec, RoleStatus, RoleTemplate, RollingUpdate,
    ScalingAdapterHook, TpuSpec,
)
from rbg_tpu.api.instance import (
    ComponentStatus, ControllerRevision, InstanceTemplate, ReadyPolicy,
    RoleInstance, RoleInstanceSet, RoleInstanceSetSpec, RoleInstanceSetStatus,
    RoleInstanceSpec, RoleInstanceStatus,
)
from rbg_tpu.api.meta import (
    Condition, ObjectMeta, OwnerReference, get_condition, owner_ref,
    set_condition,
)
from rbg_tpu.api.pod import (
    ConfigMap, Container, EnvVar, Node, NodeAffinityTerm, Pod, PodStatus,
    PodTemplate, Port, Resources, Service, TpuNodeInfo,
)
from rbg_tpu.api.policy import (
    CoordinatedPolicy, CoordinatedPolicySpec, CoordinatedRollingUpdate,
    CoordinatedScaling, EngineRuntimeProfile, PodGroup, PodGroupSpec,
    PodGroupStatus, ProgressionGate, ScalingAdapter, ScalingAdapterSpec,
    ScalingAdapterStatus, Warmup, WarmupSpec, WarmupStatus, WarmupTarget,
)
from rbg_tpu.api.serde import from_dict, load_yaml_docs, to_dict, to_yaml

KINDS = {
    cls.__name__: cls
    for cls in (
        RoleBasedGroup, RoleBasedGroupSet, RoleInstanceSet, RoleInstance,
        ControllerRevision, CoordinatedPolicy, ScalingAdapter, Warmup,
        EngineRuntimeProfile, RoleTemplate, Pod, Node, Service, ConfigMap,
        PodGroup,
    )
}


def parse_manifest(doc: dict, *, lenient: bool = False):
    """Build a typed resource from a parsed YAML document (kind-dispatched).
    ``lenient`` is for durable-storage reads (see serde.from_dict)."""
    kind = doc.get("kind")
    if kind not in KINDS:
        raise KeyError(f"unknown kind {kind!r}; known: {sorted(KINDS)}")
    return from_dict(KINDS[kind], doc, lenient=lenient)
