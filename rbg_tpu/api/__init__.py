"""Resource model (the CRD-equivalent API surface). See SURVEY.md §2 #1-3."""

from rbg_tpu.api import constants
from rbg_tpu.api.group import (
    ComponentSpec, EngineRuntimeRef, GroupTemplate, IdentityMode,
    LeaderWorkerSpec,
    PatternType, RestartPolicy, RestartPolicyConfig, RoleBasedGroup,
    RoleBasedGroupSet, RoleBasedGroupSetSpec, RoleBasedGroupSpec,
    RoleBasedGroupStatus, RoleSpec, RoleStatus, RoleTemplate, RollingUpdate,
    ScalingAdapterHook, TpuSpec,
)
from rbg_tpu.api.instance import (
    ComponentStatus, ControllerRevision, InstanceTemplate, ReadyPolicy,
    RoleInstance, RoleInstanceSet, RoleInstanceSetSpec, RoleInstanceSetStatus,
    RoleInstanceSpec, RoleInstanceStatus,
)
from rbg_tpu.api.meta import (
    Condition, ObjectMeta, OwnerReference, get_condition, owner_ref,
    set_condition,
)
from rbg_tpu.api.pod import (
    ConfigMap, Container, EnvVar, Node, NodeAffinityTerm, Pod, PodStatus,
    PodTemplate, Port, Resources, Service, TpuNodeInfo,
)
from rbg_tpu.api.policy import (
    CoordinatedPolicy, CoordinatedPolicySpec, CoordinatedRollingUpdate,
    CoordinatedScaling, EngineRuntimeProfile, PodGroup, PodGroupSpec,
    PodGroupStatus, ProgressionGate, ScalingAdapter, ScalingAdapterSpec,
    ScalingAdapterStatus, Warmup, WarmupSpec, WarmupStatus, WarmupTarget,
)
from rbg_tpu.api.serde import from_dict, load_yaml_docs, to_dict, to_yaml

KINDS = {
    cls.__name__: cls
    for cls in (
        RoleBasedGroup, RoleBasedGroupSet, RoleInstanceSet, RoleInstance,
        ControllerRevision, CoordinatedPolicy, ScalingAdapter, Warmup,
        EngineRuntimeProfile, RoleTemplate, Pod, Node, Service, ConfigMap,
        PodGroup,
    )
}


API_GROUP = "rbg.tpu.x-k8s.io"
API_VERSION = f"{API_GROUP}/v1alpha2"

# apiVersion -> converter(dict) -> dict at a NEWER apiVersion. The hub-spoke
# conversion-webhook analog (reference:
# ``api/workloads/v1alpha1/rolebasedgroup_conversion.go``), collapsed to
# pure dict->dict functions run at admission: an old manifest is converted
# forward until it reaches API_VERSION, then parsed strictly. Register a
# converter here when a release renames/restructures the manifest schema
# (docs/architecture.md §5 rule 2). v1alpha1 manifests (bool ``stateful``)
# convert live — see rbg_tpu/api/conversions.py.
MANIFEST_CONVERSIONS: dict = {}


def _register_conversions():
    from rbg_tpu.api import conversions
    MANIFEST_CONVERSIONS[f"{API_GROUP}/v1alpha1"] = (
        conversions.v1alpha1_to_v1alpha2)


_register_conversions()


def convert_manifest(doc: dict) -> dict:
    """Run the registered conversion chain until ``doc`` is at API_VERSION.
    A manifest with no apiVersion is taken as current (additive-with-
    defaults evolution needs no conversion)."""
    ver = doc.get("apiVersion") or API_VERSION
    seen = set()
    while ver != API_VERSION:
        conv = MANIFEST_CONVERSIONS.get(ver)
        if conv is None or ver in seen:
            raise KeyError(
                f"unsupported apiVersion {ver!r} (no conversion to "
                f"{API_VERSION})")
        seen.add(ver)
        doc = conv(dict(doc))
        ver = doc.get("apiVersion") or API_VERSION
    return doc


def parse_manifest(doc: dict, *, lenient: bool = False):
    """Build a typed resource from a parsed YAML document (kind-dispatched).
    ``lenient`` is for durable-storage reads (see serde.from_dict)."""
    doc = convert_manifest(doc)
    kind = doc.get("kind")
    if kind not in KINDS:
        raise KeyError(f"unknown kind {kind!r}; known: {sorted(KINDS)}")
    if "apiVersion" in doc:
        doc = {k: v for k, v in doc.items() if k != "apiVersion"}
    return from_dict(KINDS[kind], doc, lenient=lenient)
