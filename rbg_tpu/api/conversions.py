"""Live API conversions (reference analog: inventory #2,
``api/workloads/v1alpha1/rolebasedgroup_conversion.go:1-598`` hub-spoke
conversion + ``tools/crd-upgrade``).

v1alpha1 → v1alpha2 (shipped this release): the boolean ``stateful`` on role
specs became the string ``identity: "ordinal" | "random"`` — the old name
conflated the identity discipline with storage semantics the plane never
had, and a closed bool left no room for future disciplines (e.g. a
slice-affine-but-renameable mode). The conversion is exact and lossless:
``stateful: true`` (and absent) → ``"ordinal"``, ``false`` → ``"random"``.

Snapshot files carry the same shape inside ``objects`` (plus
ControllerRevision payloads holding serialized group specs), migrated by
``SNAPSHOT_MIGRATIONS[1]`` on load — both registries are exercised by
committed old-format fixtures in ``tests/fixtures/``.
"""

from __future__ import annotations


def _convert_role(role: dict) -> dict:
    role = dict(role)
    if "identity" not in role:
        stateful = role.get("stateful", True)
        role["identity"] = "ordinal" if stateful else "random"
    role.pop("stateful", None)
    return role


def _convert_group_spec(spec: dict) -> dict:
    spec = dict(spec)
    if isinstance(spec.get("roles"), list):
        spec["roles"] = [_convert_role(r) for r in spec["roles"]
                         if isinstance(r, dict)]
    return spec


def v1alpha1_to_v1alpha2(doc: dict) -> dict:
    """Convert one v1alpha1 manifest/stored-object dict to v1alpha2."""
    from rbg_tpu.api import API_GROUP

    doc = dict(doc)
    kind = doc.get("kind")
    spec = doc.get("spec")
    if kind == "RoleBasedGroup" and isinstance(spec, dict):
        doc["spec"] = _convert_group_spec(spec)
    elif kind == "RoleBasedGroupSet" and isinstance(spec, dict):
        spec = dict(spec)
        tmpl = spec.get("template")
        if isinstance(tmpl, dict) and isinstance(tmpl.get("spec"), dict):
            tmpl = dict(tmpl)
            tmpl["spec"] = _convert_group_spec(tmpl["spec"])
            spec["template"] = tmpl
        doc["spec"] = spec
    elif kind == "RoleInstanceSet" and isinstance(spec, dict):
        spec = dict(spec)
        if "identity" not in spec:
            spec["identity"] = ("ordinal" if spec.get("stateful", True)
                                else "random")
        spec.pop("stateful", None)
        doc["spec"] = spec
    elif kind == "ControllerRevision" and isinstance(doc.get("data"), dict):
        # Revision payloads hold a serialized RoleBasedGroupSpec — an undo
        # to a pre-upgrade revision must re-apply cleanly.
        doc["data"] = _convert_group_spec(doc["data"])
    if doc.get("apiVersion"):
        doc["apiVersion"] = f"{API_GROUP}/v1alpha2"
    return doc


def migrate_snapshot_v1(data: dict) -> dict:
    """Snapshot schema 1 → 2: stored objects predate the identity rename.
    (Objects in snapshots carry no apiVersion — the schema number versions
    the whole file.)"""
    data = dict(data)
    data["objects"] = [v1alpha1_to_v1alpha2(o) if isinstance(o, dict) else o
                       for o in data.get("objects", [])]
    data["schema"] = 2
    return data
