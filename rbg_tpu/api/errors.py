"""Canonical catalog of structured error codes.

Every ``code`` that rides the serving wire (``{"error": ..., "code": ...}``
frames, ``Rejected.to_wire``) or is compared against a reply's ``code``
field MUST be a constant from this module — the ``error-code-registry``
lint rule (``rbg_tpu/analysis/rules/errorcodes.py``) flags any string
literal used in a code position that is not cataloged here.

Why a registry: PRs 2-3 built the serving plane's error contract on these
strings — the HTTP edge maps them to statuses (429/503/504), the router
routes around the retryable ones, the stress harness accounts outcomes by
them. A typo ("overladed") at any of those layers silently breaks the
contract; a catalog plus a lint rule makes the break a build failure.

This module is dependency-free on purpose: the engine server imports its
codes before jax loads (see ``engine/protocol.py``), and the lint rule
parses it statically (AST), so keep it to plain ``NAME = "literal"``
assignments and simple containers.
"""

from __future__ import annotations

# ---- structured rejection codes (serving wire) ----

#: Admission control shed the request (queue full / estimated wait too
#: long). Retryable — the edge maps it to HTTP 429 + Retry-After.
CODE_OVERLOADED = "overloaded"

#: The client's end-to-end budget is spent (queued too long, or aborted
#: mid-run). Not retryable — HTTP 504.
CODE_DEADLINE = "deadline_exceeded"

#: The backend is in SIGTERM drain: in-flight work finishes, new work is
#: refused. Retryable on a sibling — HTTP 503.
CODE_DRAINING = "draining"

#: Base code of ``Rejected`` — a structured rejection that is none of the
#: specific kinds above.
CODE_REJECTED = "rejected"

#: A prefill→decode KV chunk stream failed (truncated, aborted, or never
#: became coverage-complete). The KV at that decode replica is gone — the
#: router recovers by RE-PREFILLING (pool/radix makes it cheap) rather
#: than retrying the same stream; clients never see it when a sibling
#: path exists.
CODE_KV_STREAM = "kv_stream_failed"

#: A KV payload failed its end-to-end checksum — a chunk frame whose
#: bytes do not match the checksum minted at the producer, or a cached
#: page whose bytes rotted across spill→promote / a peer fetch. Never
#: served: the receiver abandons the stream (bundle-fallback replays it
#: token-exact) or the pool treats the page as a miss. Distinct from
#: CODE_KV_STREAM so operators can tell "link flaked" from "bytes lied".
CODE_KV_INTEGRITY = "kv_integrity_failed"

#: Codes the router may retry on a sibling backend (a shed or draining
#: backend is HEALTHY — never evicted).
RETRYABLE_REJECT_CODES = (CODE_OVERLOADED, CODE_DRAINING)

#: Every cataloged code. The lint rule and the runtime registry check
#: against this set.
ALL_CODES = frozenset({
    CODE_OVERLOADED,
    CODE_DEADLINE,
    CODE_DRAINING,
    CODE_REJECTED,
    CODE_KV_STREAM,
    CODE_KV_INTEGRITY,
})

# ---- HTTP edge mapping (single source for http_frontend) ----

#: code → HTTP status. 429 tells well-behaved clients to back off
#: (Retry-After carries the backend's hint); 503 marks a draining pod a
#: load balancer should rotate out; 504 is a spent client deadline.
CODE_HTTP_STATUS = {
    CODE_OVERLOADED: 429,
    CODE_DRAINING: 503,
    CODE_DEADLINE: 504,
}

#: code → OpenAI-style error ``type`` string for the JSON error body.
CODE_HTTP_ETYPE = {
    CODE_OVERLOADED: "overloaded",
    CODE_DRAINING: "unavailable",
    CODE_DEADLINE: "timeout",
}
