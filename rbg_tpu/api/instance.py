"""RoleInstanceSet / RoleInstance — the native workload engine's resources.

Reference analog: inventory #10-13 — ``roleinstanceset_types.go`` /
``roleinstance_types.go`` (KEP-30 InstanceSet). One RoleInstance = a *gang of
pods* (a whole multi-host TPU slice for leader-worker roles); the set manages
N instances with ordered (stateful) or random (stateless) identity.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional

from rbg_tpu.api.group import (
    ComponentSpec, EngineRuntimeRef, IdentityMode, LeaderWorkerSpec,
    PatternType, RestartPolicyConfig, RollingUpdate, TpuSpec,
)
from rbg_tpu.api.meta import Condition, ObjectMeta
from rbg_tpu.api.pod import PodTemplate


class ReadyPolicy(str, enum.Enum):
    ALL_PODS_READY = "AllPodReady"
    NONE = "None"


@dataclasses.dataclass
class InstanceTemplate:
    """What one instance looks like: pattern + templates + placement."""

    pattern: PatternType = PatternType.STANDALONE
    template: PodTemplate = dataclasses.field(default_factory=PodTemplate)
    leader_worker: Optional[LeaderWorkerSpec] = None
    components: List[ComponentSpec] = dataclasses.field(default_factory=list)
    tpu: Optional[TpuSpec] = None
    ready_policy: ReadyPolicy = ReadyPolicy.ALL_PODS_READY
    engine_runtime: Optional["EngineRuntimeRef"] = None


@dataclasses.dataclass
class RoleInstanceSetSpec:
    replicas: int = 1
    identity: IdentityMode = IdentityMode.ORDINAL
    instance: InstanceTemplate = dataclasses.field(default_factory=InstanceTemplate)
    restart_policy: RestartPolicyConfig = dataclasses.field(default_factory=RestartPolicyConfig)
    rolling_update: RollingUpdate = dataclasses.field(default_factory=RollingUpdate)
    selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    # PreparingDelete drain window for stateless scale-down (0 = immediate).
    drain_seconds: float = 0.0

    @property
    def stateful(self) -> bool:
        """Derived from ``identity`` (kept for call-site readability)."""
        return self.identity != IdentityMode.RANDOM


@dataclasses.dataclass
class RoleInstanceSetStatus:
    """Rollup counters (reference: ``roleinstanceset_types.go:160-206``)."""

    observed_generation: int = 0
    replicas: int = 0
    ready_replicas: int = 0
    updated_replicas: int = 0
    updated_ready_replicas: int = 0
    current_replicas: int = 0       # instances still at current_revision
    current_revision: str = ""
    update_revision: str = ""
    conditions: List[Condition] = dataclasses.field(default_factory=list)

    @property
    def expected_updated_replicas(self) -> int:
        return self.replicas


@dataclasses.dataclass
class RoleInstanceSet:
    kind: str = "RoleInstanceSet"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: RoleInstanceSetSpec = dataclasses.field(default_factory=RoleInstanceSetSpec)
    status: RoleInstanceSetStatus = dataclasses.field(default_factory=RoleInstanceSetStatus)

    __serde_keep__ = ("kind", "metadata")


@dataclasses.dataclass
class ComponentStatus:
    """Per-component counters (reference: ``roleinstance_types.go:181-202``)."""

    name: str = ""
    size: int = 0
    ready: int = 0
    scheduled: int = 0

    __serde_keep__ = ("name",)


@dataclasses.dataclass
class RoleInstanceSpec:
    instance: InstanceTemplate = dataclasses.field(default_factory=InstanceTemplate)
    restart_policy: RestartPolicyConfig = dataclasses.field(default_factory=RestartPolicyConfig)
    index: int = -1             # ordinal for stateful instances; -1 stateless
    # Drain window for in-place updates, propagated from the set's
    # rollingUpdate.graceSeconds when an update is recorded (the pod-level
    # convergence loop needs it without reaching back to the RIS).
    inplace_grace_seconds: float = 0.0


@dataclasses.dataclass
class RoleInstanceStatus:
    phase: str = "Pending"      # Pending | Running | Restarting | Deleting
    components: List[ComponentStatus] = dataclasses.field(default_factory=list)
    conditions: List[Condition] = dataclasses.field(default_factory=list)
    restart_count: int = 0
    last_restart_time: float = 0.0
    observed_revision: str = ""
    slice_id: str = ""          # TPU slice this instance is bound to

    __serde_keep__ = ("phase",)


@dataclasses.dataclass
class RoleInstance:
    kind: str = "RoleInstance"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: RoleInstanceSpec = dataclasses.field(default_factory=RoleInstanceSpec)
    status: RoleInstanceStatus = dataclasses.field(default_factory=RoleInstanceStatus)

    __serde_keep__ = ("kind", "metadata")


@dataclasses.dataclass
class ControllerRevision:
    """Immutable snapshot of a spec for rollout history/undo (reference:
    ``pkg/utils/revision_utils.go:50-403`` + KEP-31)."""

    kind: str = "ControllerRevision"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    revision: int = 0
    data: dict = dataclasses.field(default_factory=dict)   # serialized spec
    role_hashes: Dict[str, str] = dataclasses.field(default_factory=dict)

    __serde_keep__ = ("kind", "metadata", "revision")
