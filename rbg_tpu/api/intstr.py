"""IntOrString — rolling-update knobs that accept an int or a percent.

Reference analog: ``intstr.IntOrString`` consumed via
``GetScaledValueFromIntOrPercent`` in the workload reconcilers
(``sts_reconciler.go:198-449`` percent handling). Kubernetes rounding
conventions are preserved: **maxSurge rounds UP**, **maxUnavailable rounds
DOWN** (so "25%" of 3 replicas surges 1 but only takes 0 unavailable —
the engines then floor the combined budget to 1 to keep progress).
"""

from __future__ import annotations

import math
import re
from typing import Union

IntOrStr = Union[int, str]

_PCT = re.compile(r"^(\d+)%$")


def validate(value: IntOrStr, name: str = "value") -> None:
    """Admission check: ints must be >= 0 is the caller's rule; strings
    must be a whole-number percent like ``"25%"``."""
    if isinstance(value, str):
        if not _PCT.match(value.strip()):
            raise ValueError(
                f"{name}: {value!r} is not an integer or a percent "
                f"(expected e.g. 1 or \"25%\")")


def resolve(value: IntOrStr, total: int, *, round_up: bool,
            name: str = "value") -> int:
    """Scale ``value`` against ``total`` replicas. Ints pass through."""
    if isinstance(value, str):
        m = _PCT.match(value.strip())
        if not m:
            raise ValueError(
                f"{name}: {value!r} is not an integer or a percent")
        pct = int(m.group(1))
        scaled = pct * total / 100.0
        return math.ceil(scaled) if round_up else math.floor(scaled)
    return int(value)
