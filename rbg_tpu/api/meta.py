"""Object metadata — the identity/ownership model every resource shares.

Reference analog: ``metav1.ObjectMeta`` usage throughout
``api/workloads/v1alpha2``; we keep only the fields the control plane
actually exercises (name/namespace/uid/labels/annotations/ownerRefs/
resourceVersion/generation/deletion).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional


@dataclasses.dataclass
class OwnerReference:
    kind: str = ""
    name: str = ""
    uid: str = ""
    controller: bool = True


@dataclasses.dataclass
class ObjectMeta:
    name: str = ""
    namespace: str = "default"
    uid: str = ""
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    owner_references: List[OwnerReference] = dataclasses.field(default_factory=list)
    resource_version: int = 0
    generation: int = 0
    creation_timestamp: float = 0.0
    deletion_timestamp: Optional[float] = None

    __serde_keep__ = ("name",)

    def controller_owner(self) -> Optional[OwnerReference]:
        for ref in self.owner_references:
            if ref.controller:
                return ref
        return None

    def owned_by(self, obj) -> bool:
        return any(r.uid == obj.metadata.uid for r in self.owner_references)


def owner_ref(obj, controller: bool = True) -> OwnerReference:
    return OwnerReference(
        kind=obj.kind, name=obj.metadata.name, uid=obj.metadata.uid,
        controller=controller,
    )


@dataclasses.dataclass
class Condition:
    """Status condition (k8s metav1.Condition shape)."""

    type: str = ""
    status: str = "Unknown"  # "True" | "False" | "Unknown"
    reason: str = ""
    message: str = ""
    last_transition_time: float = 0.0

    __serde_keep__ = ("type", "status")


def set_condition(conditions: List[Condition], cond: Condition, now: float) -> bool:
    """Upsert a condition; preserves lastTransitionTime when status unchanged.
    Returns True if anything changed. (Reference analog: meta.SetStatusCondition
    semantics used across controllers.)"""
    for i, c in enumerate(conditions):
        if c.type == cond.type:
            if (c.status, c.reason, c.message) == (cond.status, cond.reason, cond.message):
                return False
            cond.last_transition_time = now if c.status != cond.status else c.last_transition_time
            conditions[i] = cond
            return True
    cond.last_transition_time = now
    conditions.append(cond)
    return True


def get_condition(conditions: List[Condition], type_: str) -> Optional[Condition]:
    for c in conditions:
        if c.type == type_:
            return c
    return None
