"""Pod / Node / Service resource model.

The control plane's smallest schedulable unit. On TPU, one Pod maps to one
*host* of a TPU slice (a v5e-64 slice = 16 hosts × 4 chips); a multi-host role
instance is a gang of Pods pinned to one slice's ICI domain.

Reference analog: corev1.Pod consumed throughout ``pkg/reconciler`` — here we
model only the surface the plane exercises (containers, env, ports, node
assignment, phase/conditions), which is also exactly what the local process
executor can honor.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from rbg_tpu.api import constants as C
from rbg_tpu.api.meta import Condition, ObjectMeta


@dataclasses.dataclass
class EnvVar:
    name: str = ""
    value: str = ""


@dataclasses.dataclass
class Port:
    name: str = ""
    container_port: int = 0


@dataclasses.dataclass
class Resources:
    """Resource requests. ``tpu_chips`` is the TPU analog of the reference's
    GPU-implicit resources (``google.com/tpu`` in GKE terms)."""

    cpu: float = 0.0
    memory_gb: float = 0.0
    tpu_chips: int = 0


@dataclasses.dataclass
class Container:
    name: str = ""
    image: str = ""
    command: List[str] = dataclasses.field(default_factory=list)
    args: List[str] = dataclasses.field(default_factory=list)
    env: List[EnvVar] = dataclasses.field(default_factory=list)
    ports: List[Port] = dataclasses.field(default_factory=list)
    resources: Resources = dataclasses.field(default_factory=Resources)


@dataclasses.dataclass
class PodTemplate:
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    init_containers: List[Container] = dataclasses.field(default_factory=list)
    containers: List[Container] = dataclasses.field(default_factory=list)
    volumes: List[str] = dataclasses.field(default_factory=list)
    node_selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    scheduler_hints: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class NodeAffinityTerm:
    """Preferred/required node affinity (reference analog: the nodeAffinity
    injection of in-place scheduling, ``sync/node_binding.go:276``)."""

    key: str = ""
    operator: str = "In"  # In | NotIn | Exists | DoesNotExist
    values: List[str] = dataclasses.field(default_factory=list)
    required: bool = False
    weight: int = 1


@dataclasses.dataclass
class PodStatus:
    phase: str = "Pending"  # Pending | Running | Succeeded | Failed
    # Machine-readable failure reason (reference analog: corev1 Pod
    # status.reason — "Evicted", "UnexpectedAdmissionError", ...; consumed
    # by the inactive-pod handling of keps/inactive-pod-handling).
    reason: str = ""
    ready: bool = False
    node_name: str = ""
    pod_ip: str = ""
    restart_count: int = 0
    container_restarts: Dict[str, int] = dataclasses.field(default_factory=dict)
    conditions: List[Condition] = dataclasses.field(default_factory=list)
    observed_revision: str = ""
    start_time: float = 0.0

    __serde_keep__ = ("phase",)


@dataclasses.dataclass
class Pod:
    kind: str = "Pod"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    template: PodTemplate = dataclasses.field(default_factory=PodTemplate)
    node_name: str = ""          # scheduling result (binding)
    affinity: List[NodeAffinityTerm] = dataclasses.field(default_factory=list)
    status: PodStatus = dataclasses.field(default_factory=PodStatus)

    __serde_keep__ = ("kind", "metadata")

    @property
    def active(self) -> bool:
        """Active = not terminal and not terminating (reference analog:
        inactive-pod handling, keps/inactive-pod-handling)."""
        return (
            self.metadata.deletion_timestamp is None
            and self.status.phase not in ("Succeeded", "Failed")
        )

    @property
    def inactive_reason(self) -> str:
        """Why this pod is inactive (reference: GetPodInactiveReason,
        keps/inactive-pod-handling): Evicted / UnexpectedAdmissionError /
        the raw reason / the terminal phase / Terminating; empty = active."""
        if self.active:
            return ""
        if self.metadata.deletion_timestamp is not None:
            return "Terminating"
        if self.evicted:
            return "Evicted"
        if self.status.reason:
            return self.status.reason
        return self.status.phase  # Failed | Succeeded

    @property
    def evicted(self) -> bool:
        """Evicted by node pressure / disruption (reference: IsPodEvicted —
        Failed + reason Evicted or a DisruptionTarget condition)."""
        if self.status.phase != "Failed":
            return False
        if self.status.reason == "Evicted":
            return True
        return any(c.type == "DisruptionTarget" and c.status == "True"
                   for c in self.status.conditions)

    @property
    def inplace_update_pending(self) -> bool:
        """An in-place update readiness gate is held (reference analog: the
        InPlaceUpdateReady readinessGate, ``pkg/inplace/pod/readiness``)."""
        return any(c.type == C.COND_INPLACE_UPDATE_READY and c.status == "False"
                   for c in self.status.conditions)

    @property
    def running_ready(self) -> bool:
        return (self.active and self.status.phase == "Running"
                and self.status.ready and not self.inplace_update_pending)


@dataclasses.dataclass
class TpuNodeInfo:
    """TPU identity of a node (GKE analog: cloud.google.com/gke-tpu-topology
    and google.com/tpu labels; see SURVEY.md §7 step 5)."""

    accelerator: str = ""       # v5e | v5p | v4 ...
    slice_id: str = ""          # one ICI domain == one slice id
    slice_topology: str = ""    # e.g. "4x4"
    worker_index: int = 0       # host index within the slice
    chips: int = 0              # chips on this host
    mesh_coords: str = ""       # host coordinates within the slice, "x,y[,z]"


@dataclasses.dataclass
class Node:
    kind: str = "Node"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    tpu: TpuNodeInfo = dataclasses.field(default_factory=TpuNodeInfo)
    capacity_pods: int = 64
    ready: bool = True
    address: str = "127.0.0.1"
    # Disruption lifecycle (GKE analog: maintenance events + spot
    # preemption hit ALL hosts of a slice together — same ICI domain):
    # ``unschedulable`` is the cordon bit (kubectl cordon / spec.
    # unschedulable); ``disruption`` is "" | maintenance | preempted;
    # ``disruption_deadline`` (unix seconds) is the advance-notice window
    # end for maintenance — by then the slice must be released.
    unschedulable: bool = False
    disruption: str = ""
    disruption_deadline: float = 0.0

    __serde_keep__ = ("kind", "metadata")

    @property
    def schedulable(self) -> bool:
        return self.ready and not self.unschedulable and not self.disruption


@dataclasses.dataclass
class Service:
    """Headless-service equivalent: a stable DNS-ish name selecting pods.

    Reference analog: ``svc_reconciler.go:48-179``; per-instance FQDNs
    ``{workload}-{i}.{svc}`` are generated by discovery
    (``config_builder.go:117-138``).
    """

    kind: str = "Service"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    selector: Dict[str, str] = dataclasses.field(default_factory=dict)
    leader_only: bool = False   # sharedServiceSelection: LeaderOnly (KEP-260)

    __serde_keep__ = ("kind", "metadata")


@dataclasses.dataclass
class ConfigMap:
    """Key→string data bundle mounted into pods (discovery topology config;
    reference analog: corev1.ConfigMap written by
    ``pkg/discovery/config_builder.go``)."""

    kind: str = "ConfigMap"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    data: Dict[str, str] = dataclasses.field(default_factory=dict)

    __serde_keep__ = ("kind", "metadata")
