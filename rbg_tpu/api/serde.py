"""Dataclass ⇄ dict/YAML serialization with camelCase wire format.

The reference's public contract is CRD YAML (SURVEY.md §1 L6,
``config/crd/bases/*.yaml``); ours is the same shape of contract — YAML
manifests in camelCase — backed by plain Python dataclasses instead of Go
structs + codegen (inventory #26's 15k generated lines collapse into this one
reflective module).
"""

from __future__ import annotations

import dataclasses
import enum
import re
import typing
from typing import Any, Type, TypeVar, get_args, get_origin

T = TypeVar("T")

_CAMEL_RE = re.compile(r"_([a-z0-9])")
_SNAKE_RE = re.compile(r"(?<!^)(?=[A-Z])")


def to_camel(s: str) -> str:
    return _CAMEL_RE.sub(lambda m: m.group(1).upper(), s)


def to_snake(s: str) -> str:
    return _SNAKE_RE.sub("_", s).lower()


def to_dict(obj: Any, *, drop_default: bool = True) -> Any:
    """Serialize a dataclass tree to plain dicts (camelCase keys).

    Fields equal to their default are dropped (keeps manifests/diffs small),
    except fields named in the class's ``__serde_keep__`` tuple.
    """
    if dataclasses.is_dataclass(obj) and not isinstance(obj, type):
        keep = getattr(obj, "__serde_keep__", ())
        skip = getattr(obj, "__serde_skip__", ())
        out = {}
        for f in dataclasses.fields(obj):
            if f.name in skip:
                continue   # derived/in-memory-only fields never serialize
            v = getattr(obj, f.name)
            if drop_default and f.name not in keep:
                if f.default is not dataclasses.MISSING and v == f.default:
                    continue
                if f.default_factory is not dataclasses.MISSING and v == f.default_factory():  # type: ignore[misc]
                    continue
            out[to_camel(f.name)] = to_dict(v, drop_default=drop_default)
        return out
    if isinstance(obj, enum.Enum):
        return obj.value
    if isinstance(obj, dict):
        return {k: to_dict(v, drop_default=drop_default) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [to_dict(v, drop_default=drop_default) for v in obj]
    return obj


def from_dict(cls: Type[T], data: Any, *, lenient: bool = False) -> T:
    """Deserialize camelCase dicts into dataclass ``cls``.

    Strict mode (default) rejects unknown keys — admission-style schema
    checking, reference analog: CEL validation on CRDs
    (``api/workloads/v1alpha2/*_types.go`` kubebuilder markers). A typo in
    a user manifest must be an error, never a silent no-op.

    ``lenient=True`` drops unknown keys (logged once per key) — for data
    read back from DURABLE storage (state-file snapshots, stored
    ControllerRevisions), which may have been written by a newer release
    (schema-evolution Rule 3, docs/architecture.md §5)."""
    return _build(cls, data, path="$", lenient=lenient)


_warned_unknown: set = set()


def _build(tp: Any, data: Any, path: str, lenient: bool = False) -> Any:
    origin = get_origin(tp)
    if tp is Any:
        return data
    if origin is typing.Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        if data is None:
            return None
        if len(args) == 1:
            return _build(args[0], data, path, lenient)
        # Multi-arm unions (IntOrString): first arm that accepts the data.
        last_err: Exception = TypeError(f"{path}: no union arm matched")
        for arm in args:
            try:
                return _build(arm, data, path, lenient)
            except (TypeError, ValueError, KeyError) as e:
                last_err = e
        raise last_err
    if origin in (list, tuple):
        if not isinstance(data, list):
            raise TypeError(f"{path}: expected list, got {type(data).__name__}")
        (elem,) = get_args(tp) or (Any,)
        return [_build(elem, v, f"{path}[{i}]", lenient) for i, v in enumerate(data)]
    if origin is dict:
        if not isinstance(data, dict):
            raise TypeError(f"{path}: expected object, got {type(data).__name__}")
        kt, vt = get_args(tp) or (str, Any)
        return {k: _build(vt, v, f"{path}.{k}", lenient) for k, v in data.items()}
    if isinstance(tp, type) and issubclass(tp, enum.Enum):
        return tp(data)
    if dataclasses.is_dataclass(tp):
        if data is None:
            data = {}
        if not isinstance(data, dict):
            raise TypeError(f"{path}: expected object for {tp.__name__}, got {type(data).__name__}")
        fields = {f.name: f for f in dataclasses.fields(tp)}
        hints = typing.get_type_hints(tp)
        kwargs = {}
        for k, v in data.items():
            name = to_snake(k)
            if name not in fields:
                if lenient:
                    marker = (tp.__name__, k)
                    if marker not in _warned_unknown:
                        _warned_unknown.add(marker)
                        import logging
                        logging.getLogger("rbg_tpu.serde").warning(
                            "dropping unknown field %r for %s (written by a "
                            "newer release?)", k, tp.__name__)
                    continue
                raise KeyError(f"{path}: unknown field {k!r} for {tp.__name__}")
            kwargs[name] = _build(hints[fields[name].name], v, f"{path}.{k}", lenient)
        return tp(**kwargs)
    if tp in (int, float, str, bool):
        if tp is float and isinstance(data, int):
            return float(data)
        if not isinstance(data, tp):
            raise TypeError(f"{path}: expected {tp.__name__}, got {type(data).__name__}")
        return data
    return data


def to_yaml(obj: Any) -> str:
    import yaml

    return yaml.safe_dump(to_dict(obj), sort_keys=False)


def load_yaml_docs(text: str):
    import yaml

    return [d for d in yaml.safe_load_all(text) if d]
