"""Label / annotation / env-var contracts — the per-object config plane.

Reference analog: ``api/workloads/constants`` (inventory #3,
``label.go:22-102``, ``annotation.go:22-228``, ``env.go:24-79``). Same role
here: labels ARE the data-plane contract (discovery reads them), annotations
are per-object feature flags, envs are what engines consume.

TPU-specific additions replace the GPU-era rendezvous contract
(``RBG_LWP_LEADER_ADDRESS`` consumed as torch ``--dist-init-addr``,
``env.go:56-68``) with the JAX distributed-init contract: coordinator
address, process index/count, slice topology, and mesh coordinates.
"""

DOMAIN = "rbg.tpu.x-k8s.io"

# ---- labels (identity; reference: label.go:22-102) ----
LABEL_GROUP_NAME = f"{DOMAIN}/group-name"
LABEL_ROLE_NAME = f"{DOMAIN}/role-name"
LABEL_GROUP_SET_NAME = f"{DOMAIN}/groupset-name"
LABEL_GROUP_SET_INDEX = f"{DOMAIN}/groupset-index"
LABEL_INSTANCE_NAME = f"{DOMAIN}/role-instance-name"
LABEL_INSTANCE_INDEX = f"{DOMAIN}/role-instance-index"
LABEL_COMPONENT_NAME = f"{DOMAIN}/component-name"
LABEL_COMPONENT_ID = f"{DOMAIN}/component-id"
LABEL_COMPONENT_INDEX = f"{DOMAIN}/component-index"
LABEL_SLICE_ORDINAL = f"{DOMAIN}/slice-ordinal"   # sub-gang id in multi-slice roles
LABEL_GROUP_REVISION = f"{DOMAIN}/group-revision"
LABEL_ROLE_REVISION_PREFIX = f"{DOMAIN}/role-revision-"
LABEL_REVISION_NAME = f"{DOMAIN}/revision-name"
LABEL_POD_GROUP = f"{DOMAIN}/pod-group"

# ---- annotations (feature flags; reference: annotation.go:22-228) ----
ANN_GANG_SCHEDULING = f"{DOMAIN}/gang-scheduling"        # "true"/"false"
ANN_EXCLUSIVE_TOPOLOGY = f"{DOMAIN}/exclusive-topology"  # topology key
ANN_INSTANCE_PATTERN = f"{DOMAIN}/role-instance-pattern"  # stateful|stateless
ANN_RESTART_TRIGGER_POLICY = f"{DOMAIN}/restart-trigger-policy"  # Ignore
# In-place scheduling (KEP-351; reference node_binding.go): mode is
# Preferred | Required | Disabled (our default when unset is Preferred —
# warm rebinding is the point of TPU slices; the reference defaults to off).
ANN_INPLACE_SCHEDULING = f"{DOMAIN}/in-place-scheduling"
# Pod | Component; unset = auto (stateful→Pod, stateless→Component,
# reference resolveGranularity, node_binding.go:191).
ANN_INPLACE_SCHEDULING_GRANULARITY = f"{DOMAIN}/in-place-scheduling-granularity"
# Comma-separated label keys → DoesNotExist node terms (avoid labels,
# node_binding.go:276 step 3).
ANN_INPLACE_SCHEDULING_AVOID = f"{DOMAIN}/in-place-scheduling-avoid"
ANN_PORT_ALLOCATOR = f"{DOMAIN}/port-allocator"          # JSON config
ANN_ALLOCATED_PORTS = f"{DOMAIN}/allocated-ports"        # JSON result
ANN_COMPONENT_DEPENDS_ON = f"{DOMAIN}/component-depends-on"  # JSON
ANN_SLICE_BINDING = f"{DOMAIN}/slice-binding"            # recorded slice id
# In-place update state on a Pod: JSON {revision, images, restarted,
# baselines, notReadyAt, grace} (reference analog: Kruise's
# apps.kruise.io/inplace-update-state, pkg/inplace inplace_update.go:223-316).
ANN_INPLACE_UPDATE_STATE = f"{DOMAIN}/inplace-update-state"
# PreparingDelete lifecycle (stateless scale-down drain; reference:
# statelessmode lifecycle states, constants.go:75-80): the instance keeps
# serving in-flight work until a drain agent acks (drain-complete=true) or
# the deadline passes, and may be resurrected by a scale-up.
ANN_LIFECYCLE_STATE = f"{DOMAIN}/lifecycle-state"    # PreparingDelete
ANN_DRAIN_DEADLINE = f"{DOMAIN}/drain-deadline"      # unix seconds
ANN_DRAIN_COMPLETE = f"{DOMAIN}/drain-complete"      # "true" from drain agent
LIFECYCLE_PREPARING_DELETE = "PreparingDelete"
ANN_DISCOVERY_CONFIG_MODE = f"{DOMAIN}/discovery-config-mode"  # legacy|refine

# ---- autoscaler contract (SLO-driven coordinated autoscaling) ----
# On a ScalingAdapter: the replica value the autoscaler last wrote. When
# spec.replicas differs from this stamp at the next evaluation, a FOREIGN
# writer (an external HPA, an operator) touched the adapter since our last
# write — the autoscaler backs off for one cycle and adopts the foreign
# value as its new baseline instead of silently clobbering it
# (last-writer-wins is how two controllers fight forever).
ANN_AUTOSCALE_LAST_WRITE = f"{DOMAIN}/autoscale-last-write"
# On a RoleInstance: scale-down preference stamped by the autoscaler from
# observed in-flight streams (lowest cost retired first — the k8s
# pod-deletion-cost analog). Consumed by the stateless instance engine's
# victim ordering; absent reads as 0.
ANN_SCALE_DOWN_COST = f"{DOMAIN}/scale-down-cost"

# ---- adaptive topology contract (aggregation <-> disaggregation) ----
# On a RoleBasedGroup, the runtime PD-shape state machine driven by the
# topology controller. Annotations are the ONLY persistent state — a
# plane restart resumes a mid-flight flip from them (same discipline as
# the migration state machine above).
ANN_TOPOLOGY_POSTURE = f"{DOMAIN}/topology-posture"    # unified|disagg
ANN_TOPOLOGY_STATE = f"{DOMAIN}/topology-state"        # Warming|CutOver|Draining
ANN_TOPOLOGY_TARGET = f"{DOMAIN}/topology-target"      # unified|disagg
ANN_TOPOLOGY_STARTED = f"{DOMAIN}/topology-flip-started"  # unix seconds
# Roles currently eligible for NEW traffic (JSON list) — the router
# candidacy set the cutover phase flips role-by-role.
ANN_TOPOLOGY_SERVING = f"{DOMAIN}/topology-serving-roles"

# ---- slice disruption lifecycle (GKE TPU failure domains) ----
# On a RoleInstance, the advance-notice migration state machine driven by
# the disruption controller: "" -> Warming -> CutOver -> (cleared).
ANN_MIGRATION_STATE = f"{DOMAIN}/migration-state"
ANN_MIGRATION_TARGET = f"{DOMAIN}/migration-target"    # target slice id
ANN_MIGRATION_FROM = f"{DOMAIN}/migration-from"        # source slice id
ANN_MIGRATION_DEADLINE = f"{DOMAIN}/migration-deadline"  # unix seconds
MIGRATION_WARMING = "Warming"
MIGRATION_CUTOVER = "CutOver"
# On a Node, stamped by the disruption controller once no active pod
# remains on a maintenance-pending slice: the slice is handed back to the
# infrastructure before its deadline (value = unix seconds of release).
ANN_MAINT_RELEASED = f"{DOMAIN}/maintenance-released"
# Marks a cordon the disruption controller itself placed ("disruption") —
# only those may be auto-lifted or kept sticky across node resyncs;
# operator cordons are never touched.
ANN_CORDONED_BY = f"{DOMAIN}/cordoned-by"
# Node disruption kinds (Node.disruption field / K8s node conditions).
DISRUPT_MAINTENANCE = "maintenance"   # advance notice, deadline attached
DISRUPT_PREEMPTED = "preempted"       # no-notice spot preemption
# Pod failure reasons the gang-recovery path recognizes.
REASON_PREEMPTED = "Preempted"        # host vanished under the pod
REASON_GANG_PREEMPTED = "GangPreempted"  # survivor killed by gang semantics

# ---- env vars injected into engine processes (reference: env.go:24-79) ----
ENV_GROUP_NAME = "RBG_GROUP_NAME"
ENV_ROLE_NAME = "RBG_ROLE_NAME"
ENV_ROLE_INDEX = "RBG_ROLE_INDEX"
ENV_ROLE_REPLICAS = "RBG_ROLE_REPLICAS"
ENV_COMPONENT_NAME = "RBG_COMPONENT_NAME"
ENV_CONFIG_PATH = "RBG_CONFIG_PATH"     # topology config mount path
ENV_POD_NAME = "RBG_POD_NAME"

# JAX distributed-init contract for multi-host slice roles. These replace the
# reference's leader-worker envs (RBG_LWP_LEADER_ADDRESS / RBG_LWP_WORKER_INDEX /
# RBG_LWP_GROUP_SIZE, env.go:56-68): engines call
# jax.distributed.initialize(coordinator_address, num_processes, process_id).
ENV_JAX_COORDINATOR = "RBG_JAX_COORDINATOR_ADDRESS"
ENV_JAX_NUM_PROCESSES = "RBG_JAX_NUM_PROCESSES"
ENV_JAX_PROCESS_ID = "RBG_JAX_PROCESS_ID"
ENV_TPU_SLICE_TOPOLOGY = "RBG_TPU_SLICE_TOPOLOGY"   # e.g. "2x4"
ENV_TPU_ACCELERATOR = "RBG_TPU_ACCELERATOR"         # e.g. "v5e"
ENV_TPU_MESH_COORDS = "RBG_TPU_MESH_COORDS"         # host coords in slice, "x,y"
ENV_MEGASCALE_COORDINATOR = "MEGASCALE_COORDINATOR_ADDRESS"  # multi-slice DCN
ENV_MEGASCALE_NUM_SLICES = "MEGASCALE_NUM_SLICES"
ENV_MEGASCALE_SLICE_ID = "MEGASCALE_SLICE_ID"
# Bumped on every gang restart cycle: a replacement gang must never join a
# stale coordinator incarnation mid-collective (the JAX coordinator treats a
# changed epoch as a fresh rendezvous namespace).
ENV_JAX_RESTART_EPOCH = "RBG_JAX_RESTART_EPOCH"

# ---- defaults ----
DISCOVERY_MOUNT_PATH = "/etc/rbg"
DISCOVERY_CONFIG_FILE = "config.yaml"
MAX_NAME_LEN = 63

# ---- condition types ----
COND_READY = "Ready"
COND_UPDATE_IN_PROGRESS = "UpdateInProgress"
COND_RESTART_IN_PROGRESS = "Restarting"
COND_ALL_PODS_READY = "AllPodsReady"
COND_INPLACE_UPDATE_READY = "InPlaceUpdateReady"


def workload_name(group: str, role: str) -> str:
    """Child workload name ``{group}-{role}`` truncated to 63 chars with
    trailing '-' trimmed (reference: helper.go:87-100)."""
    return f"{group}-{role}"[:MAX_NAME_LEN].rstrip("-")


def service_name(group: str, role: str) -> str:
    """Headless-service name ``s-{group}-{role}`` (DNS-1035: must not start
    with a digit — reference: helper.go:106-115)."""
    return f"s-{group}-{role}"[:MAX_NAME_LEN].rstrip("-")


def role_revision_label(role: str) -> str:
    return (LABEL_ROLE_REVISION_PREFIX + role)[:MAX_NAME_LEN]
