"""RoleBasedGroup — the root resource: a list of coordinated roles.

Reference analog: ``api/workloads/v1alpha2/rolebasedgroup_types.go`` (inventory
#1): ``RoleSpec`` (:203), patterns standalone/leaderWorker/customComponents
(:300-312, :335, :368-433), ``RestartPolicyConfig`` backoff (:164-187),
``EngineRuntime`` hook (:392-402). TPU-first change: ``leaderWorkerPattern.size``
(how many GPU nodes form one model instance) becomes ``TpuSpec.slice_topology``
— one role replica = one multi-host TPU slice, and the plane derives the gang
size from the topology instead of asking for a raw count.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional

from rbg_tpu.api.intstr import IntOrStr
from rbg_tpu.api.meta import Condition, ObjectMeta
from rbg_tpu.api.pod import PodTemplate


class PatternType(str, enum.Enum):
    STANDALONE = "standalone"
    LEADER_WORKER = "leaderWorker"
    CUSTOM_COMPONENTS = "customComponents"


class IdentityMode(str, enum.Enum):
    """Instance identity discipline (v1alpha2 rename of the v1alpha1 bool
    ``stateful``, converted in api/conversions.py). Enum-typed so admission
    strict-parse rejects misspellings ("Random", "stateless") instead of
    silently running the role ordinal."""

    ORDINAL = "ordinal"   # stable {set}-{i} names, slice-pinned placement
    RANDOM = "random"     # CloneSet-like unordered instances


@dataclasses.dataclass
class ComponentSpec:
    """One component of a customComponents role (reference: :368-433 +
    KEP-173): heterogeneous intra-role groups (router + worker + cache)."""

    name: str = ""
    size: int = 1
    template: Optional[PodTemplate] = None
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class LeaderWorkerSpec:
    """Leader + N-1 workers per role instance. ``size`` may be omitted for TPU
    roles — it is then derived from tpu.slice_topology (hosts per slice)."""

    size: int = 0
    leader_template: Optional[PodTemplate] = None  # defaults to role template
    worker_template: Optional[PodTemplate] = None


@dataclasses.dataclass
class TpuSpec:
    """First-class TPU placement request for a role.

    Replaces the reference's GPU-implicit knobs (BASELINE.json north star):
    one role replica occupies one ``slice_topology`` slice of ``accelerator``
    chips; the plane gang-places its hosts into a single ICI domain and
    injects the JAX coordinator + mesh coordinates (rbg_tpu.discovery).
    """

    accelerator: str = ""       # v5e | v5p | v6e ...
    slice_topology: str = ""    # e.g. "2x4" (chips); hosts derived per accel
    chips_per_host: int = 4
    # Multi-slice (MEGASCALE) role: one instance spans num_slices slices —
    # ICI within each slice, DCN across them. The plane places one sub-gang
    # per slice and injects the MEGASCALE_* env contract.
    num_slices: int = 1

    @property
    def total_chips(self) -> int:
        if not self.slice_topology:
            return 0
        n = 1
        for part in self.slice_topology.lower().split("x"):
            n *= int(part)
        return n

    @property
    def num_hosts(self) -> int:
        chips = self.total_chips
        if chips == 0:
            return 0
        return max(1, chips // max(1, self.chips_per_host))


def per_slice_size(leader_worker, tpu) -> int:
    """Pods per slice sub-gang of a leaderWorker role: explicit
    ``leader_worker.size`` wins, else derived from the slice topology.
    The ONE definition shared by gang sizing, pod naming, slice-ordinal
    labeling and MEGASCALE env math — these must never diverge."""
    if leader_worker is not None and leader_worker.size:
        return leader_worker.size
    if tpu is not None and tpu.num_hosts:
        return tpu.num_hosts
    return 1


class RestartPolicy(str, enum.Enum):
    NONE = "None"
    RECREATE_INSTANCE_ON_POD_RESTART = "RecreateRoleInstanceOnPodRestart"
    RECREATE_GROUP_ON_POD_RESTART = "RecreateGroupOnPodRestart"


@dataclasses.dataclass
class RestartPolicyConfig:
    """Restart policy + exponential backoff (reference: :164-187; backoff math
    ``min(base·2^(n-1), max)`` in ``sync/instance_scale.go:482-506``)."""

    policy: RestartPolicy = RestartPolicy.RECREATE_INSTANCE_ON_POD_RESTART
    base_delay_seconds: float = 1.0
    max_delay_seconds: float = 300.0
    window_seconds: float = 600.0   # restart-count decay window


@dataclasses.dataclass
class RollingUpdate:
    """Rolling update knobs (reference: RIS update strategy,
    ``roleinstanceset_reconciler.go:231-252``). ``max_unavailable`` and
    ``max_surge`` accept an int or a percent string ("25%"), scaled
    against role replicas with K8s rounding (surge up, unavailable down —
    ``api/intstr.py``; reference ``sts_reconciler.go:198-449``)."""

    max_unavailable: IntOrStr = 1
    max_surge: IntOrStr = 0
    partition: int = 0
    in_place_if_possible: bool = True
    # Freeze rollout progress mid-flight; existing surge is preserved
    # (reference: UpdateStrategy.Paused, computeTopology paused branch).
    paused: bool = False
    # Seconds an instance must be Ready before it counts as available for
    # the rolling-update budget (reference: getMinReadySeconds).
    min_ready_seconds: int = 0
    # In-place update drain window: the pod sits InPlaceUpdateReady=False
    # for this long BEFORE its images are patched, so routers/endpoints can
    # drain it (reference: InPlaceUpdateStrategy.GracePeriodSeconds,
    # ``inplace_update.go:258-283``).
    grace_seconds: float = 0.0


@dataclasses.dataclass
class ScalingAdapterHook:
    """Auto-create a ScalingAdapter for this role (reference: KEP-29,
    ``rolebasedgroup_controller.go:896-953``)."""

    enabled: bool = False
    min_replicas: int = 0
    max_replicas: int = 0


@dataclasses.dataclass
class EngineRuntimeRef:
    """Reference to an EngineRuntimeProfile + per-container overrides
    (reference: ``rolebasedgroup_types.go:392-402``)."""

    profile_name: str = ""
    container_args: Dict[str, List[str]] = dataclasses.field(default_factory=dict)
    container_env: Dict[str, Dict[str, str]] = dataclasses.field(default_factory=dict)


SUBDOMAIN_SHARED = "Shared"
SUBDOMAIN_UNIQUE_PER_REPLICA = "UniquePerReplica"


@dataclasses.dataclass
class NetworkConfig:
    """Per-role network policy (KEP-275, ``keps/275-enhance-network``).

    ``Shared`` (default): one headless service for the whole role —
    ``{pod}.s-{group}-{role}``. ``UniquePerReplica``: one headless service
    PER RoleInstance, named after the instance (``{pod}.{instance}``); the
    shared role service is removed in steady state. UniquePerReplica
    requires the leaderWorker pattern (stable per-replica identity) —
    rejected at admission otherwise, never silently downgraded."""

    subdomain_policy: str = SUBDOMAIN_SHARED


@dataclasses.dataclass
class RoleSpec:
    name: str = ""
    replicas: int = 1
    dependencies: List[str] = dataclasses.field(default_factory=list)
    pattern: PatternType = PatternType.STANDALONE
    leader_worker: Optional[LeaderWorkerSpec] = None
    components: List[ComponentSpec] = dataclasses.field(default_factory=list)
    template: PodTemplate = dataclasses.field(default_factory=PodTemplate)
    template_ref: str = ""      # RoleTemplate name (KEP-8 yaml-dedup)
    tpu: Optional[TpuSpec] = None
    restart_policy: RestartPolicyConfig = dataclasses.field(default_factory=RestartPolicyConfig)
    rolling_update: RollingUpdate = dataclasses.field(default_factory=RollingUpdate)
    scaling_adapter: Optional[ScalingAdapterHook] = None
    engine_runtime: Optional[EngineRuntimeRef] = None
    identity: IdentityMode = IdentityMode.ORDINAL
    workload: str = "RoleInstanceSet"  # strategy selector (inventory #23)
    # Scale-down drain window (stateless mode): an instance slated for
    # deletion enters PreparingDelete and keeps serving in-flight work for
    # up to this long (or until a drain agent acks) before the pods die
    # (reference: statelessmode preparingDelete lifecycle,
    # ``api/workloads/constants/constants.go:75-80``).
    drain_seconds: float = 0.0
    # KEP-260 sharedServiceSelection: "All" exposes every pod through the
    # role service; "LeaderOnly" exposes only instance leaders (component
    # index 0) — routers then address one endpoint per multi-host instance.
    service_selection: str = "All"     # All | LeaderOnly
    # Role-level networking (KEP-275): how headless services map to the
    # role's replicas.
    network: Optional["NetworkConfig"] = None

    __serde_keep__ = ("name",)

    @property
    def stateful(self) -> bool:
        """Derived from ``identity`` (kept for call-site readability)."""
        return self.identity != IdentityMode.RANDOM

    def gang_size(self) -> int:
        """Pods per role instance."""
        if self.pattern == PatternType.LEADER_WORKER:
            return per_slice_size(self.leader_worker, self.tpu) * (
                max(1, self.tpu.num_slices) if self.tpu else 1)
        if self.pattern == PatternType.CUSTOM_COMPONENTS:
            return sum(c.size for c in self.components) or 1
        return 1


@dataclasses.dataclass
class RoleStatus:
    name: str = ""
    replicas: int = 0
    ready_replicas: int = 0
    updated_replicas: int = 0
    updated_ready_replicas: int = 0
    observed_revision: str = ""
    # Rolled up from the RoleInstanceSet's Ready condition (capacity-aware
    # during surge rollouts) rather than re-derived from the counters.
    # DERIVED state: recomputed by the first reconcile after a state-file
    # load, so it is excluded from serialization (__serde_skip__) — a
    # snapshot written by this release must still load on the previous,
    # strict-parsing one (schema-evolution Rule 1, docs/architecture.md §5).
    ready: bool = False

    __serde_keep__ = ("name", "replicas", "ready_replicas")
    __serde_skip__ = ("ready",)


@dataclasses.dataclass
class RoleBasedGroupSpec:
    roles: List[RoleSpec] = dataclasses.field(default_factory=list)

    def role(self, name: str) -> Optional[RoleSpec]:
        for r in self.roles:
            if r.name == name:
                return r
        return None


@dataclasses.dataclass
class RoleBasedGroupStatus:
    observed_generation: int = 0
    roles: List[RoleStatus] = dataclasses.field(default_factory=list)
    conditions: List[Condition] = dataclasses.field(default_factory=list)
    current_revision: str = ""

    def role(self, name: str) -> Optional[RoleStatus]:
        for r in self.roles:
            if r.name == name:
                return r
        return None


@dataclasses.dataclass
class RoleBasedGroup:
    kind: str = "RoleBasedGroup"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: RoleBasedGroupSpec = dataclasses.field(default_factory=RoleBasedGroupSpec)
    status: RoleBasedGroupStatus = dataclasses.field(default_factory=RoleBasedGroupStatus)

    __serde_keep__ = ("kind", "metadata")


@dataclasses.dataclass
class GroupTemplate:
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: RoleBasedGroupSpec = dataclasses.field(default_factory=RoleBasedGroupSpec)


@dataclasses.dataclass
class RoleBasedGroupSetSpec:
    replicas: int = 1
    template: GroupTemplate = dataclasses.field(default_factory=GroupTemplate)
    # Fleet rollout staging: at most this many child groups (int or
    # percent of replicas, rounded down) may be unavailable (not Ready) at
    # once while template changes propagate.
    # <=0 = unbounded (update every drifted group simultaneously — the
    # reference's behavior, ``rolebasedgroupset_controller.go:168-177``);
    # the default of 1 rolls the fleet one cell at a time, each cell's own
    # rolling-update machinery staging its pods in turn.
    max_unavailable: IntOrStr = 1


@dataclasses.dataclass
class RoleBasedGroupSetStatus:
    replicas: int = 0
    ready_replicas: int = 0
    # In-range child groups whose spec/labels/annotations match the current
    # template (fleet-rollout progress counter).
    updated_replicas: int = 0
    observed_generation: int = 0


@dataclasses.dataclass
class RoleBasedGroupSet:
    """Replicated RBGs from a template (reference: inventory #7,
    ``rolebasedgroupset_controller.go``)."""

    kind: str = "RoleBasedGroupSet"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    spec: RoleBasedGroupSetSpec = dataclasses.field(default_factory=RoleBasedGroupSetSpec)
    status: RoleBasedGroupSetStatus = dataclasses.field(default_factory=RoleBasedGroupSetStatus)

    __serde_keep__ = ("kind", "metadata")


@dataclasses.dataclass
class RoleTemplate:
    """Reusable role template (KEP-8 reduce-yaml-duplication)."""

    kind: str = "RoleTemplate"
    metadata: ObjectMeta = dataclasses.field(default_factory=ObjectMeta)
    template: PodTemplate = dataclasses.field(default_factory=PodTemplate)

    __serde_keep__ = ("kind", "metadata")
