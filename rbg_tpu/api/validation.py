"""Admission-style validation.

Reference analog: ``rolebasedgroup_admission.go:42-84`` +
``rolebasedgroup_validation.go:31-153`` (webhook validation). Here it runs at
the store boundary / controller entry instead of an HTTP webhook — same
checks, same failure surface (reject before any child object is created).
"""

from __future__ import annotations

import re
from typing import List

from rbg_tpu.api.group import PatternType, RoleBasedGroup

_DNS_RE = re.compile(r"^[a-z0-9]([-a-z0-9]*[a-z0-9])?$")


class ValidationError(Exception):
    def __init__(self, errors):
        if isinstance(errors, str):
            errors = [errors]
        self.errors = list(errors)
        super().__init__("; ".join(self.errors))


def validate_group(rbg: RoleBasedGroup) -> None:
    errs: List[str] = []
    if not rbg.metadata.name or not _DNS_RE.match(rbg.metadata.name):
        errs.append(f"metadata.name {rbg.metadata.name!r} must be DNS-1123")
    seen = set()
    names = {r.name for r in rbg.spec.roles}
    for i, role in enumerate(rbg.spec.roles):
        path = f"spec.roles[{i}]"
        if not role.name or not _DNS_RE.match(role.name):
            errs.append(f"{path}.name {role.name!r} must be DNS-1123")
        if role.name in seen:
            errs.append(f"{path}.name {role.name!r} duplicated")
        seen.add(role.name)
        if role.replicas < 0:
            errs.append(f"{path}.replicas must be >= 0")
        for d in role.dependencies:
            if d not in names:
                errs.append(f"{path} depends on unknown role {d!r}")
            if d == role.name:
                errs.append(f"{path} depends on itself")
        if role.pattern == PatternType.LEADER_WORKER:
            lw_size = role.leader_worker.size if role.leader_worker else 0
            if not lw_size and not (role.tpu and role.tpu.slice_topology):
                errs.append(f"{path}: leaderWorker needs leaderWorker.size or tpu.sliceTopology")
        if role.pattern == PatternType.CUSTOM_COMPONENTS and not role.components:
            errs.append(f"{path}: customComponents needs components")
        if role.tpu and role.tpu.slice_topology:
            if not re.match(r"^\d+(x\d+)*$", role.tpu.slice_topology):
                errs.append(f"{path}.tpu.sliceTopology {role.tpu.slice_topology!r} invalid")
        if role.network is not None:
            from rbg_tpu.api.group import (SUBDOMAIN_SHARED,
                                           SUBDOMAIN_UNIQUE_PER_REPLICA)
            pol = role.network.subdomain_policy
            if pol not in (SUBDOMAIN_SHARED, SUBDOMAIN_UNIQUE_PER_REPLICA):
                errs.append(f"{path}.network.subdomainPolicy {pol!r} must be "
                            f"Shared or UniquePerReplica")
            elif (pol == SUBDOMAIN_UNIQUE_PER_REPLICA
                  and role.pattern != PatternType.LEADER_WORKER):
                # KEP-275 eligibility: only leaderWorker has the stable
                # per-replica identity per-instance services need. Reject,
                # never silently fall back.
                errs.append(f"{path}.network.subdomainPolicy UniquePerReplica "
                            f"requires pattern leaderWorker")
        from rbg_tpu.api import intstr
        for knob in ("max_unavailable", "max_surge"):
            try:
                intstr.validate(getattr(role.rolling_update, knob),
                                f"{path}.rollingUpdate.{knob}")
            except ValueError as e:
                errs.append(str(e))
    if not rbg.spec.roles:
        errs.append("spec.roles must not be empty")
    # cycle check
    try:
        from rbg_tpu.coordination.dependency import sort_roles
        sort_roles(rbg.spec.roles)
    except Exception as e:
        errs.append(str(e))
    if errs:
        raise ValidationError(errs)
