"""Canonical catalog of wire ops: the cross-plane request/reply contract.

Every op that rides a socket between rbg-tpu processes — the admin plane
(``runtime/admin.py``), the engine data plane (``engine/server.py``), the
kv-pool / directory plane (``engine/kvpool.py``), and the router plane
(``engine/router.py``) — is declared HERE, once: its name, owning
plane(s), auth gate, request fields (required/optional + coarse type),
reply fields per outcome, and the error codes it may return
(⊆ ``api/errors.ALL_CODES``).

Why a registry: the plane speaks ~30 ops across four server surfaces and
eight-plus client call sites. A reply field a client reads but no server
sets — or an op/error-code that exists on one side only — is silent
drift an e2e test catches only by luck. The catalog makes the contract a
build artifact: the ``op-registry`` / ``field-discipline`` /
``error-code-flow`` lint rules (``analysis/rules/wire.py``) audit both
sides statically, and the ``RBG_WIRECHECK`` sentry
(``utils/wirecheck.py``) validates live frames against the same specs.
Same playbook as ``api/errors.py`` (PR 4) and the ``BUCKET_FNS`` catalog
(PR 19): declare once, lint both directions, arm a runtime sentry.

This module is dependency-free on purpose (stdlib ``typing`` only): the
lint rules and the wirecheck sentry import it without jax, and the
engine server imports its constants before jax loads.

Conventions (see docs/static-analysis.md for the adding-an-op checklist):

* request field types are coarse (``int``/``float``/``str``/``bool``/
  ``tokens``/``list``/``dict``/``any``); a ``?`` suffix marks the field
  optional, everything else is required on the wire;
* ``response`` maps outcome name → reply field tuple; validators use the
  union across outcomes (streamed ops emit several frame shapes);
* error frames are universal: any reply may instead be
  ``{"error", "code"?, "retry_after_s"?, "done"?}`` (``REPLY_ERROR_FIELDS``)
  — only the ``code`` value is per-op, gated by ``errors``;
* ``REQUEST_UNIVERSAL`` fields (``op``/``token``/``trace``/``timeout_s``/
  ``page_size``) are stamped by transport helpers onto any request and
  are never declared per op;
* keys starting with ``_`` are process-local annotations (e.g. the
  router's ``_router_t_dispatch`` TTFT stamp) — they never cross the
  wire and validators ignore them.
"""

from __future__ import annotations

from typing import Dict, NamedTuple, Tuple

from rbg_tpu.api.errors import (ALL_CODES, CODE_DEADLINE, CODE_DRAINING,
                                CODE_KV_INTEGRITY, CODE_KV_STREAM,
                                CODE_OVERLOADED, CODE_REJECTED)

# ---- op name constants (import these; never inline the literal) ----

OP_HEALTH = "health"
OP_METRICS = "metrics"
OP_SLO = "slo"
OP_TRACES = "traces"

# admin plane
OP_LIST = "list"
OP_GET = "get"
OP_APPLY = "apply"
OP_DELETE = "delete"
OP_STATUS = "status"
OP_HISTORY = "history"
OP_DIFF = "diff"
OP_UNDO = "undo"
OP_AUTOSCALE = "autoscale"
OP_TOPOLOGY = "topology"
OP_PROFILE = "profile"
OP_EVENTS = "events"
OP_CONTROLPLANE = "controlplane"
OP_HA = "ha"

# engine data plane
OP_WARMUP = "warmup"
OP_GENERATE = "generate"
OP_GENERATE_TEXT = "generate_text"
OP_EMBED = "embed"
OP_PREFILL = "prefill"
OP_DECODE_BUNDLE = "decode_bundle"
OP_KV_STREAM = "kv_stream"
OP_DECODE_STREAM = "decode_stream"

# KV chunk-stream sub-frames (ride the decode server's kv_stream socket
# and the standalone transport listener; kvtransfer/transport.py)
OP_KV_META = "kv_meta"
OP_KV_CHUNK = "kv_chunk"
OP_KV_FIRST = "kv_first"
OP_KV_FIN = "kv_fin"

# kv-pool / directory plane
OP_POOL_MATCH = "pool_match"
OP_POOL_PUT = "pool_put"
OP_POOL_STATS = "pool_stats"
OP_DIR_REGISTER = "dir_register"
OP_DIR_LOOKUP = "dir_lookup"
OP_DIR_INVALIDATE = "dir_invalidate"
OP_DIR_STATS = "dir_stats"

PLANE_ADMIN = "admin"
PLANE_ENGINE = "engine"
PLANE_KVPOOL = "kvpool"
PLANE_ROUTER = "router"

# ---- universal fields ----

#: Stamped onto any request by transport/client helpers (token gates,
#: per-hop deadline rebudgeting, trace propagation, the kv-pool
#: page-size handshake). Never declared per op.
REQUEST_UNIVERSAL = frozenset({"op", "token", "trace", "timeout_s",
                               "page_size"})

#: Any reply may be a structured error frame instead of its declared
#: outcome. ``code`` values are gated per op by ``OpSpec.errors``.
REPLY_ERROR_FIELDS = frozenset({"error", "code", "retry_after_s", "done"})

#: Added/consumed by the codec itself (``send_msg``/``recv_msg`` binary
#: payload lengths) — framing, not contract.
FRAMING_FIELDS = frozenset({"bin_k", "bin_v"})


class OpSpec(NamedTuple):
    """One op's wire contract. ``request`` maps field → coarse type
    (``?`` suffix = optional); ``response`` maps outcome → reply fields;
    ``errors`` are the ``code`` values this op may return."""

    op: str
    plane: str
    auth: bool
    request: Dict[str, str]
    response: Dict[str, Tuple[str, ...]]
    errors: Tuple[str, ...] = ()


def request_fields(spec: OpSpec) -> frozenset:
    return frozenset(spec.request)


def required_fields(spec: OpSpec) -> frozenset:
    return frozenset(f for f, t in spec.request.items()
                     if not t.endswith("?"))


def reply_fields(spec: OpSpec) -> frozenset:
    out = set()
    for fields in spec.response.values():
        out.update(fields)
    return frozenset(out)


# Sampling knobs ride generate/prefill/decode requests verbatim
# (SamplingParams.from_wire, engine/config.py; forwarded by the router's
# _FWD_DECODE_KEYS). All optional.
_SAMPLING_REQ = {
    "max_new_tokens": "int?",
    "temperature": "float?",
    "top_k": "int?",
    "top_p": "float?",
    "min_p": "float?",
    "repetition_penalty": "float?",
    "presence_penalty": "float?",
    "frequency_penalty": "float?",
    "seed": "int?",
    "logprobs": "bool?",
    "json_mode": "bool?",
    "regex": "str?",
    "json_schema": "dict?",
    "lora": "str?",
    "stop_token": "int?",
}

# Shared operator-payload reply shapes (obs/slo.py::slo_response,
# obs/trace.py::traces_response, obs/profiler.py::sample_profile) — the
# admin plane and the engine server serve the same helpers.
SLO_RESPONSE_FIELDS = ("window_s", "sampler", "signals",
                       "signals_by_window", "cache", "trackers")
TRACES_RESPONSE_FIELDS = ("recent", "slowest", "active", "waterfall",
                          "exemplars")
PROFILE_RESPONSE_FIELDS = ("seconds", "samples", "top", "folded")

# Reject codes a generation-style op can return: admission shed, spent
# budget, SIGTERM drain, or the structured base rejection.
_GEN_ERRORS = (CODE_OVERLOADED, CODE_DEADLINE, CODE_DRAINING,
               CODE_REJECTED)

# Streamed generation reply outcomes: blocking reply, incremental stream
# frames, the terminal done frame.
_GEN_RESPONSE = {
    "ok": ("tokens", "ttft_s", "logprobs"),
    "stream": ("tokens", "logprobs", "done"),
    "final": ("tokens", "done", "ttft_s"),
}


def _spec(op: str, plane: str, auth: bool, request: Dict[str, str],
          response: Dict[str, Tuple[str, ...]],
          errors: Tuple[str, ...] = ()) -> OpSpec:
    return OpSpec(op, plane, auth, request, response, errors)


# ---- admin plane (runtime/admin.py; bearer token on all but health) ----

ADMIN_OPS: Dict[str, OpSpec] = {
    OP_HEALTH: _spec(OP_HEALTH, PLANE_ADMIN, False, {},
                     {"ok": ("ok", "disruption", "spare_pool")}),
    OP_LIST: _spec(OP_LIST, PLANE_ADMIN, True,
                   {"kind": "str", "namespace": "str?", "all": "bool?"},
                   {"ok": ("items",)}),
    OP_GET: _spec(OP_GET, PLANE_ADMIN, True,
                  {"kind": "str", "name": "str", "namespace": "str?"},
                  {"ok": ("object",)}),
    OP_APPLY: _spec(OP_APPLY, PLANE_ADMIN, True, {"manifest": "str"},
                    {"ok": ("ok", "kind", "name")}),
    OP_DELETE: _spec(OP_DELETE, PLANE_ADMIN, True,
                     {"kind": "str", "name": "str", "namespace": "str?"},
                     {"ok": ("ok",)}),
    OP_STATUS: _spec(OP_STATUS, PLANE_ADMIN, True,
                     {"name": "str", "namespace": "str?"},
                     {"ok": ("name", "ready", "reason", "revision",
                             "roles", "specReplicas", "pods")}),
    OP_HISTORY: _spec(OP_HISTORY, PLANE_ADMIN, True,
                      {"name": "str", "namespace": "str?"},
                      {"ok": ("revisions",)}),
    OP_DIFF: _spec(OP_DIFF, PLANE_ADMIN, True,
                   {"name": "str", "revision": "int?",
                    "namespace": "str?"},
                   {"ok": ("revision", "diff")}),
    OP_UNDO: _spec(OP_UNDO, PLANE_ADMIN, True,
                   {"name": "str", "revision": "int?",
                    "namespace": "str?"},
                   {"ok": ("ok", "restoredRevision")}),
    OP_METRICS: _spec(OP_METRICS, PLANE_ADMIN, True, {},
                      {"ok": ("text",)}),
    OP_SLO: _spec(OP_SLO, PLANE_ADMIN, True, {"window": "float?"},
                  {"ok": SLO_RESPONSE_FIELDS}),
    OP_AUTOSCALE: _spec(OP_AUTOSCALE, PLANE_ADMIN, True,
                        {"enable": "str?", "disable": "str?"},
                        {"ok": ("autoscale",)}),
    OP_TOPOLOGY: _spec(OP_TOPOLOGY, PLANE_ADMIN, True,
                       {"enable": "str?", "disable": "str?",
                        "namespace": "str?"},
                       {"ok": ("topology",)}),
    OP_TRACES: _spec(OP_TRACES, PLANE_ADMIN, True, {"n": "int?"},
                     {"ok": TRACES_RESPONSE_FIELDS}),
    OP_PROFILE: _spec(OP_PROFILE, PLANE_ADMIN, True,
                      {"seconds": "float?"},
                      {"ok": PROFILE_RESPONSE_FIELDS}),
    OP_EVENTS: _spec(OP_EVENTS, PLANE_ADMIN, True,
                     {"namespace": "str?", "kind": "str?", "name": "str?",
                      "limit": "int?", "since": "float?", "reason": "str?",
                      "type": "str?"},
                     {"ok": ("events", "stats")}),
    OP_CONTROLPLANE: _spec(OP_CONTROLPLANE, PLANE_ADMIN, True, {},
                           {"ok": ("controlplane",)}),
    OP_HA: _spec(OP_HA, PLANE_ADMIN, True, {},
                 {"ok": ("ha",)}),
}

# ---- engine data plane (engine/server.py; token on data ops) ----

ENGINE_OPS: Dict[str, OpSpec] = {
    OP_HEALTH: _spec(OP_HEALTH, PLANE_ENGINE, False, {},
                     {"ok": ("ok", "mode", "draining", "draining_for_s")}),
    OP_WARMUP: _spec(OP_WARMUP, PLANE_ENGINE, True,
                     {"input_len": "int?"},
                     {"ok": ("ok", "elapsed_s")}),
    OP_METRICS: _spec(OP_METRICS, PLANE_ENGINE, False, {},
                      {"ok": ("metrics", "mode")}),
    OP_SLO: _spec(OP_SLO, PLANE_ENGINE, False, {"window": "float?"},
                  {"ok": SLO_RESPONSE_FIELDS}),
    OP_TRACES: _spec(OP_TRACES, PLANE_ENGINE, True, {"n": "int?"},
                     {"ok": TRACES_RESPONSE_FIELDS}),
    OP_GENERATE: _spec(OP_GENERATE, PLANE_ENGINE, True,
                       {"prompt": "tokens", "stream": "bool?",
                        **_SAMPLING_REQ},
                       _GEN_RESPONSE, _GEN_ERRORS),
    OP_GENERATE_TEXT: _spec(OP_GENERATE_TEXT, PLANE_ENGINE, True,
                            {"text": "str", **_SAMPLING_REQ},
                            {"ok": ("text", "tokens", "ttft_s")},
                            _GEN_ERRORS),
    OP_EMBED: _spec(OP_EMBED, PLANE_ENGINE, True,
                    {"prompts": "list?", "text": "str?",
                     "prompt": "tokens?"},
                    {"ok": ("embeddings", "dim", "prompt_tokens",
                            "embedding")},
                    (CODE_DRAINING,)),
    OP_PREFILL: _spec(OP_PREFILL, PLANE_ENGINE, True,
                      {"prompt": "tokens", "push_to": "str?",
                       "stream_id": "str?", **_SAMPLING_REQ},
                      {"pushed": ("pushed", "stream_id", "first_token",
                                  "prompt", "kv_bytes", "push_error",
                                  "link_rates"),
                       "bundle": ("prompt", "first_token", "shape",
                                  "dtype")},
                      _GEN_ERRORS),
    OP_DECODE_BUNDLE: _spec(OP_DECODE_BUNDLE, PLANE_ENGINE, True,
                            {"prompt": "tokens", "first_token": "int",
                             "shape": "list", "dtype": "str",
                             "stream": "bool?", **_SAMPLING_REQ},
                            _GEN_RESPONSE, _GEN_ERRORS),
    OP_KV_STREAM: _spec(OP_KV_STREAM, PLANE_ENGINE, True,
                        {"stream_id": "str"},
                        {"ok": ("ok", "bytes")}),
    OP_DECODE_STREAM: _spec(OP_DECODE_STREAM, PLANE_ENGINE, True,
                            {"stream_id": "str", "stream": "bool?",
                             **_SAMPLING_REQ},
                            _GEN_RESPONSE,
                            _GEN_ERRORS + (CODE_KV_STREAM,
                                           CODE_KV_INTEGRITY)),
    # KV chunk-stream sub-frames: requests with no per-frame reply (the
    # FIN ack is the kv_stream op's reply). kv_fin's "error" is a
    # REQUEST field here — the sender reports its abort reason.
    OP_KV_META: _spec(OP_KV_META, PLANE_ENGINE, False,
                      {"stream_id": "str", "prompt": "tokens",
                       "n_pages": "int", "k_page_shape": "list",
                       "v_page_shape": "list", "dtype": "str",
                       "layers": "int", "page_size": "int"},
                      {}),
    OP_KV_CHUNK: _spec(OP_KV_CHUNK, PLANE_ENGINE, False,
                       {"stream_id": "str", "seq": "int",
                        "layer_lo": "int", "layer_hi": "int",
                        "page_lo": "int", "page_hi": "int",
                        "checksum": "int?"},
                       {}),
    OP_KV_FIRST: _spec(OP_KV_FIRST, PLANE_ENGINE, False,
                       {"stream_id": "str", "first_token": "int"},
                       {}),
    OP_KV_FIN: _spec(OP_KV_FIN, PLANE_ENGINE, False,
                     {"stream_id": "str", "n_chunks": "int",
                      "aborted": "bool?", "error": "str?"},
                     {"ok": ("ok", "bytes")}),
}

# ---- kv-pool / directory plane (engine/kvpool.py; token on all but
# health; page_size handshake on pool_match/pool_put) ----

KVPOOL_OPS: Dict[str, OpSpec] = {
    OP_HEALTH: _spec(OP_HEALTH, PLANE_KVPOOL, False, {},
                     {"ok": ("ok", "mode")}),
    OP_POOL_MATCH: _spec(OP_POOL_MATCH, PLANE_KVPOOL, True,
                         {"prompt": "tokens"},
                         {"miss": ("matched",),
                          "hit": ("matched", "k_shape", "v_shape",
                                  "dtype", "checksum")}),
    OP_POOL_PUT: _spec(OP_POOL_PUT, PLANE_KVPOOL, True,
                       {"prompt": "tokens", "k_shape": "list",
                        "v_shape": "list", "dtype": "str"},
                       {"ok": ("stored_pages",)}),
    OP_POOL_STATS: _spec(OP_POOL_STATS, PLANE_KVPOOL, True, {},
                         {"ok": ("metrics", "mode", "directory")}),
    # `metrics` aliases pool_stats on this plane (same reply shape).
    OP_METRICS: _spec(OP_METRICS, PLANE_KVPOOL, True, {},
                      {"ok": ("metrics", "mode", "directory")}),
    OP_DIR_REGISTER: _spec(OP_DIR_REGISTER, PLANE_KVPOOL, True,
                           {"keys": "list?", "backend": "str?",
                            "slice_id": "str?", "tier": "str?"},
                           {"ok": ("registered",)}),
    OP_DIR_LOOKUP: _spec(OP_DIR_LOOKUP, PLANE_KVPOOL, True,
                         {"keys": "list?", "prompt": "tokens?",
                          "detail": "bool?"},
                         {"ok": ("matched", "matched_tokens", "holders",
                                 "detail")}),
    OP_DIR_INVALIDATE: _spec(OP_DIR_INVALIDATE, PLANE_KVPOOL, True,
                             {"keys": "list?", "backend": "str?",
                              "slice_id": "str?", "reason": "str?"},
                             {"ok": ("invalidated",)}),
    OP_DIR_STATS: _spec(OP_DIR_STATS, PLANE_KVPOOL, True, {},
                        {"ok": ("directory", "mode")}),
}

# ---- router plane (engine/router.py; token on embed/generate and the
# privileged half of health) ----

ROUTER_OPS: Dict[str, OpSpec] = {
    OP_HEALTH: _spec(OP_HEALTH, PLANE_ROUTER, False, {},
                     {"ok": ("ok", "pd", "draining", "router_id"),
                      "authorized": ("inactive_roles", "metrics",
                                     "backends", "draining_backends",
                                     "retry_budget", "kv", "slo")}),
    OP_GENERATE: _spec(OP_GENERATE, PLANE_ROUTER, True,
                       {"prompt": "tokens", "stream": "bool?",
                        **_SAMPLING_REQ},
                       _GEN_RESPONSE,
                       _GEN_ERRORS + (CODE_KV_STREAM,
                                      CODE_KV_INTEGRITY)),
    OP_EMBED: _spec(OP_EMBED, PLANE_ROUTER, True,
                    {"prompts": "list?", "text": "str?",
                     "prompt": "tokens?"},
                    {"ok": ("embeddings", "dim", "prompt_tokens",
                            "embedding")},
                    (CODE_OVERLOADED, CODE_DEADLINE, CODE_DRAINING,
                     CODE_REJECTED)),
}

#: plane name → catalog. The lint rules map server modules onto planes
#: through this (analysis/rules/wire.py::PLANE_MODULES).
PLANES: Dict[str, Dict[str, OpSpec]] = {
    PLANE_ADMIN: ADMIN_OPS,
    PLANE_ENGINE: ENGINE_OPS,
    PLANE_KVPOOL: KVPOOL_OPS,
    PLANE_ROUTER: ROUTER_OPS,
}

#: Every cataloged op name, across planes.
ALL_OP_NAMES = frozenset(op for cat in PLANES.values() for op in cat)


def _merge() -> Dict[str, dict]:
    """Per-op view merged across planes (a client can't know statically
    which plane an address serves): required = intersection (a field
    every plane demands), request/reply/errors = union."""
    merged: Dict[str, dict] = {}
    for plane, cat in PLANES.items():
        for op, spec in cat.items():
            m = merged.setdefault(op, {
                "required": None, "request": set(), "reply": set(),
                "errors": set(), "planes": [],
            })
            req = required_fields(spec)
            m["required"] = (req if m["required"] is None
                             else m["required"] & req)
            m["request"] |= request_fields(spec)
            m["reply"] |= reply_fields(spec)
            m["errors"] |= set(spec.errors)
            m["planes"].append(plane)
    for m in merged.values():
        m["required"] = frozenset(m["required"] or ())
        m["request"] = frozenset(m["request"])
        m["reply"] = frozenset(m["reply"])
        m["errors"] = frozenset(m["errors"])
        m["planes"] = tuple(m["planes"])
    return merged


#: op → {"required", "request", "reply", "errors", "planes"} — the view
#: the runtime wirecheck sentry and the client-side lint checks consume.
MERGED: Dict[str, dict] = _merge()

# Catalog self-check: declared codes must exist in the error registry —
# a typo'd code here would teach both validators to accept it.
for _cat in PLANES.values():
    for _s in _cat.values():
        _bad = set(_s.errors) - ALL_CODES
        if _bad:
            raise ValueError(
                f"op {_s.op!r} ({_s.plane}) declares unknown error "
                f"code(s) {sorted(_bad)} — not in api/errors.ALL_CODES")
del _cat, _s, _bad
