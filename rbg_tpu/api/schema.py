"""JSON Schema generation from the resource dataclasses.

Reference analog: the kubebuilder-generated CRD YAML (``config/crd/bases``,
10 files) — the machine-readable API contract users validate manifests
against. Here the dataclasses ARE the source of truth; this module emits
draft-07 JSON Schemas from them (``rbg-tpu schema``).
"""

from __future__ import annotations

import dataclasses
import enum
import typing
from typing import Any, get_args, get_origin

from rbg_tpu.api.serde import to_camel


def _type_schema(tp: Any, defs: dict) -> dict:
    origin = get_origin(tp)
    if tp is Any:
        return {}
    if origin is typing.Union:
        args = [a for a in get_args(tp) if a is not type(None)]
        if len(args) == 1:
            # Optionals: absence is allowed; null not serialized
            return _type_schema(args[0], defs)
        if set(args) == {int, str}:
            # IntOrString (rolling-update knobs): int or "25%".
            return {"oneOf": [{"type": "integer"},
                              {"type": "string", "pattern": r"^\d+%$"}]}
        return {"oneOf": [_type_schema(a, defs) for a in args]}
    if origin in (list, tuple):
        (elem,) = get_args(tp) or (Any,)
        return {"type": "array", "items": _type_schema(elem, defs)}
    if origin is dict:
        _, vt = get_args(tp) or (str, Any)
        return {"type": "object", "additionalProperties": _type_schema(vt, defs)}
    if isinstance(tp, type) and issubclass(tp, enum.Enum):
        return {"type": "string", "enum": [e.value for e in tp]}
    if dataclasses.is_dataclass(tp):
        name = tp.__name__
        if name not in defs:
            defs[name] = None  # placeholder breaks recursion
            props = {}
            hints = typing.get_type_hints(tp)
            for f in dataclasses.fields(tp):
                props[to_camel(f.name)] = _type_schema(hints[f.name], defs)
            doc = (tp.__doc__ or "").strip().split("\n")[0]
            if doc.startswith(f"{name}("):
                doc = ""  # auto-generated dataclass signature, not a doc
            defs[name] = {
                "type": "object",
                "properties": props,
                "additionalProperties": False,
                **({"description": doc} if doc else {}),
            }
        return {"$ref": f"#/definitions/{name}"}
    if tp is int:
        return {"type": "integer"}
    if tp is float:
        return {"type": "number"}
    if tp is bool:
        return {"type": "boolean"}
    if tp is str:
        return {"type": "string"}
    return {}


def schema_for(cls) -> dict:
    defs: dict = {}
    root = _type_schema(cls, defs)
    ref = root.get("$ref", "").rsplit("/", 1)[-1]
    body = defs.pop(ref)
    return {
        "$schema": "http://json-schema.org/draft-07/schema#",
        "title": cls.__name__,
        **body,
        "definitions": defs,
    }


def all_schemas() -> dict:
    from rbg_tpu.api import KINDS
    return {kind: schema_for(cls) for kind, cls in sorted(KINDS.items())}
