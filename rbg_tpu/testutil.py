"""Test fixtures: fake TPU fleets + manifest builders.

Reference analog: ``test/wrappers/v1alpha2/*`` builder fixtures +
``test/stress/templates.go`` kwok node templates (SURVEY.md §4). Nodes carry
the TPU identity labels a GKE TPU nodepool would
(slice id / topology / worker index).
"""

from __future__ import annotations

from typing import List

from rbg_tpu.api.group import (
    LeaderWorkerSpec, PatternType, RoleBasedGroup, RoleSpec, TpuSpec,
)
from rbg_tpu.api.pod import Container, Node, PodTemplate, TpuNodeInfo


def make_tpu_nodes(store, slices: int = 2, hosts_per_slice: int = 2,
                   accelerator: str = "v5e", chips_per_host: int = 4) -> List[Node]:
    """Create ``slices`` fake slices × ``hosts_per_slice`` hosts each."""
    out = []
    for s in range(slices):
        for h in range(hosts_per_slice):
            n = Node()
            n.metadata.name = f"slice-{s}-host-{h}"
            n.metadata.namespace = "default"
            sid = f"slice-{s}"
            n.labels = {
                "tpu-accelerator": accelerator,
                "tpu-slice": sid,
                "topology.rbg.tpu/block": f"block-{s // 4}",
            }
            n.tpu = TpuNodeInfo(
                accelerator=accelerator, slice_id=sid,
                slice_topology=f"{hosts_per_slice * chips_per_host // 2}x2",
                worker_index=h, chips=chips_per_host,
                mesh_coords=f"{h},0",
            )
            out.append(store.create(n))
    return out


def simple_container(name: str = "engine", image: str = "engine:v1",
                     args: List[str] = ()) -> Container:
    return Container(name=name, image=image, command=["serve"], args=list(args))


def simple_role(name: str, replicas: int = 1, dependencies=(),
                image: str = "engine:v1") -> RoleSpec:
    return RoleSpec(
        name=name, replicas=replicas, dependencies=list(dependencies),
        template=PodTemplate(containers=[simple_container(image=image)]),
    )


def tpu_leaderworker_role(name: str, replicas: int = 1, topology: str = "2x4",
                          accelerator: str = "v5e", image: str = "engine:v1",
                          chips_per_host: int = 4) -> RoleSpec:
    return RoleSpec(
        name=name, replicas=replicas,
        pattern=PatternType.LEADER_WORKER,
        leader_worker=LeaderWorkerSpec(),
        tpu=TpuSpec(accelerator=accelerator, slice_topology=topology,
                    chips_per_host=chips_per_host),
        template=PodTemplate(containers=[simple_container(image=image)]),
    )


def make_group(name: str, *roles: RoleSpec, namespace: str = "default",
               annotations=None) -> RoleBasedGroup:
    g = RoleBasedGroup()
    g.metadata.name = name
    g.metadata.namespace = namespace
    g.metadata.annotations = dict(annotations or {})
    g.spec.roles = list(roles)
    return g
