"""Transports for KV chunk streams.

One explicit seam (the ROADMAP refactor): senders publish frames through a
``Transport``; receivers iterate them. Implementations:

* ``InProcTransport``  — queue-backed, same process (the PDPair path).
* ``TCPTransport``     — frames over the project's length-prefixed wire
  (``protocol.send_msg`` binary lanes); the DCN analog. The server side is
  whoever accepts the socket (the decode server's ``kv_stream`` op) — this
  class is the CLIENT half plus the frame codec both halves share.
* ``FakeICITransport`` — in-proc with modeled link pacing (bytes/sec +
  per-frame latency): the intra-slice interconnect stand-in the bench and
  stress drills measure overlap against.
* ``SlowLossyTransport`` — wrapper injecting delay, reordering, duplicate
  delivery, and truncation into any inner transport (stress
  ``--kv-slow-link``).

Every implementation reports OBSERVED transfer rates through ``LinkStats``
(`rbg_kvtransfer_link_bytes_per_s` et al) — the router's transfer-cost
scoring reads measured rates, never configured hopes.
"""

from __future__ import annotations

import queue
import random
import socket
import threading
import time
from typing import Dict, Iterator, List, Optional

from rbg_tpu.kvtransfer.chunks import (Frame, KVChunk, StreamError,
                                       StreamFin, StreamFirstToken,
                                       StreamMeta)
from rbg_tpu.obs import names as obs_names
from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.utils.locktrace import named_lock

_FIN_SENTINEL = object()


class LinkStats:
    """Measured per-link throughput (EWMA over real transfers). Keyed by
    an arbitrary peer/transport label; thread-safe leaf state."""

    ALPHA = 0.3
    MIN_SAMPLE_BYTES = 1 << 12   # ignore tiny frames; latency dominates

    def __init__(self, transport: str):
        self.transport = transport
        self._lock = named_lock("kvtransfer.linkstats")
        self._rate: Dict[str, float] = {}  # guarded_by[kvtransfer.linkstats]

    def observe(self, peer: str, nbytes: int, seconds: float) -> None:
        if seconds <= 0 or nbytes < self.MIN_SAMPLE_BYTES:
            return
        rate = nbytes / seconds
        with self._lock:
            prev = self._rate.get(peer)
            cur = rate if prev is None else \
                (1 - self.ALPHA) * prev + self.ALPHA * rate
            self._rate[peer] = cur
        REGISTRY.set_gauge(obs_names.KVT_LINK_RATE, cur,
                           transport=self.transport, peer=peer)

    def rate(self, peer: str, default: Optional[float] = None) -> Optional[float]:
        with self._lock:
            return self._rate.get(peer, default)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._rate)


class Transport:
    """Contract: ``send_chunks`` publishes an ORDERED frame sequence for a
    stream (meta first, fin last — the sender's obligation); the receiver
    side tolerates reorder/duplication anyway. ``recv_chunks`` yields
    frames until FIN (inclusive) or raises ``StreamError`` on a broken
    stream; a ``timeout`` bounds each wait so a dead sender can never
    wedge a decode thread."""

    name = "base"

    def __init__(self):
        self.stats = LinkStats(self.name)

    # -- sender half --
    def send_chunks(self, peer: str, frames) -> int:
        """Send all frames (iterable, possibly lazily produced); returns
        payload bytes moved. Blocking — run inside a sender thread when
        the producer must not stall on the link."""
        t0 = time.monotonic()
        nbytes = 0
        for frame in frames:
            self.send_one(peer, frame)
            if isinstance(frame, KVChunk):
                nbytes += frame.nbytes
                REGISTRY.inc(obs_names.KVT_CHUNKS_TOTAL, direction="sent")
        if nbytes:
            REGISTRY.inc(obs_names.KVT_BYTES_TOTAL, float(nbytes),
                         direction="sent", transport=self.name)
            self.stats.observe(peer, nbytes, time.monotonic() - t0)
        return nbytes

    def send_one(self, peer: str, frame: Frame) -> None:
        raise NotImplementedError

    # -- receiver half --
    def recv_chunks(self, stream_id: str,
                    timeout: float = 30.0) -> Iterator[Frame]:
        raise NotImplementedError


class InProcTransport(Transport):
    """Queue-per-stream transport for same-process PD pairs. ``peer`` is
    ignored (there is only one receiver side)."""

    name = "inproc"

    def __init__(self):
        super().__init__()
        self._lock = named_lock("kvtransfer.inproc")
        self._streams: Dict[str, queue.Queue] = {}  # guarded_by[kvtransfer.inproc]

    def _q(self, stream_id: str) -> queue.Queue:
        with self._lock:
            q = self._streams.get(stream_id)
            if q is None:
                q = self._streams[stream_id] = queue.Queue()
            return q

    def send_one(self, peer: str, frame: Frame) -> None:
        sid = getattr(frame, "stream_id", None)
        if sid is None:
            raise ValueError(f"frame without stream_id: {frame!r}")
        self._q(sid).put(frame)

    def recv_chunks(self, stream_id: str,
                    timeout: float = 30.0) -> Iterator[Frame]:
        q = self._q(stream_id)
        while True:
            try:
                frame = q.get(timeout=timeout)
            except queue.Empty:
                raise StreamError(
                    f"stream {stream_id}: no frame within {timeout}s "
                    f"(sender dead or link stalled)") from None
            yield frame
            if isinstance(frame, StreamFin):
                with self._lock:
                    self._streams.pop(stream_id, None)
                return


class FakeICITransport(InProcTransport):
    """In-proc transport with modeled link pacing: each frame is delayed
    by per-frame latency + payload/bandwidth, on the SENDER side (the
    producer hands frames to a pacer thread via ``send_chunks`` — use a
    sender thread when the producer must overlap). Models an ICI/DCN hop
    well enough for overlap A/Bs without real remote memory."""

    name = "fake_ici"

    def __init__(self, bytes_per_s: float = 512e6,
                 latency_s: float = 0.0005):
        super().__init__()
        self.bytes_per_s = float(bytes_per_s)
        self.latency_s = float(latency_s)

    def send_one(self, peer: str, frame: Frame) -> None:
        pay = frame.nbytes if isinstance(frame, KVChunk) else 0
        delay = self.latency_s + (pay / self.bytes_per_s
                                  if self.bytes_per_s > 0 else 0.0)
        if delay > 0:
            time.sleep(delay)
        super().send_one(peer, frame)


class SlowLossyTransport(Transport):
    """Fault-injecting wrapper for stress: per-frame delay, bounded
    reordering (a frame may overtake up to ``reorder_window`` queued
    predecessors), duplicate delivery, and optional truncation (drop all
    frames of a chosen stream past a byte budget, then deliver a
    FIN(aborted) so the receiver surfaces a structured error).

    META is never reordered ahead of nothing / behind data of its own
    stream beyond the window — the assembler tolerates any order anyway
    (it is constructed from META by the registry, which waits for it)."""

    name = "slow_lossy"

    def __init__(self, inner: Transport, delay_s: float = 0.02,
                 reorder_window: int = 3, dup_rate: float = 0.0,
                 truncate_stream: Optional[str] = None,
                 truncate_after_bytes: int = 0,
                 truncate_nth_stream: Optional[int] = None, seed: int = 0):
        super().__init__()
        self.inner = inner
        self.delay_s = delay_s
        self.reorder_window = reorder_window
        self.dup_rate = dup_rate
        self.truncate_stream = truncate_stream
        self.truncate_after_bytes = truncate_after_bytes
        # Convenience for drills: cut the Nth DISTINCT stream this link
        # carries (stream ids are minted per attempt, so a retry of the
        # victim rides a fresh id and passes).
        self.truncate_nth_stream = truncate_nth_stream
        self._streams_seen = 0
        self._rng = random.Random(seed)
        self._lock = named_lock("kvtransfer.slowlossy")
        self._sent_bytes: Dict[str, int] = {}  # guarded_by[kvtransfer.slowlossy]
        self._cut: set = set()                 # guarded_by[kvtransfer.slowlossy]
        self._pending: List[Frame] = []        # guarded_by[kvtransfer.slowlossy]

    def _truncated(self, frame: Frame) -> Optional[Frame]:
        sid = getattr(frame, "stream_id", "")
        if sid != self.truncate_stream:
            return frame
        with self._lock:
            if sid in self._cut:
                return None   # everything past the cut is dropped
            seen = self._sent_bytes.get(sid, 0)
            if isinstance(frame, KVChunk):
                seen += frame.nbytes
                self._sent_bytes[sid] = seen
            if seen > self.truncate_after_bytes:
                # Past the budget: this and later frames are dropped; the
                # stream's close becomes one aborted FIN so the receiver
                # gets a structured error, not a silent wedge.
                self._cut.add(sid)
                return StreamFin(sid, n_chunks=0, aborted=True,
                                 error="link truncated the stream")
        return frame

    def send_one(self, peer: str, frame: Frame) -> None:
        if self.delay_s > 0:
            time.sleep(self.delay_s)
        if isinstance(frame, StreamMeta) \
                and self.truncate_nth_stream is not None:
            with self._lock:
                if self._streams_seen == self.truncate_nth_stream \
                        and self.truncate_stream is None:
                    self.truncate_stream = frame.stream_id
                self._streams_seen += 1
        frame = self._truncated(frame)
        if frame is None:
            return
        emit: List[Frame] = []
        with self._lock:
            fin = isinstance(frame, StreamFin)
            flush = fin or isinstance(frame, StreamFirstToken)
            if not flush:
                self._pending.append(frame)
            # Flush in shuffled order once the window fills. Control
            # frames flush everything and go LAST in their flush: the
            # receive loop stops at FIN (an overtaking FIN would read as
            # false truncation), and a sender wants the first token
            # visible the moment it exists — reordering applies to
            # data/meta frames only.
            if len(self._pending) > self.reorder_window or flush:
                self._rng.shuffle(self._pending)
                emit, self._pending = self._pending, []
            if flush:
                emit.append(frame)
        for f in emit:
            self.inner.send_one(peer, f)
            if isinstance(f, KVChunk) and self._rng.random() < self.dup_rate:
                self.inner.send_one(peer, f)

    def recv_chunks(self, stream_id: str,
                    timeout: float = 30.0) -> Iterator[Frame]:
        return self.inner.recv_chunks(stream_id, timeout=timeout)


# ---- TCP frame codec (shared by client half and server ops) -------------


def frame_to_wire(frame: Frame):
    """(header, k_bytes, v_bytes) for ``protocol.send_msg``."""
    if isinstance(frame, StreamMeta):
        return ({"op": "kv_meta", "stream_id": frame.stream_id,
                 "prompt": list(frame.prompt), "n_pages": frame.n_pages,
                 "k_page_shape": list(frame.k_page_shape),
                 "v_page_shape": list(frame.v_page_shape),
                 "dtype": frame.dtype, "layers": frame.layers,
                 "page_size": frame.page_size}, None, None)
    if isinstance(frame, KVChunk):
        hdr = {"op": "kv_chunk", "stream_id": frame.stream_id,
               "seq": frame.seq, "layer_lo": frame.layer_lo,
               "layer_hi": frame.layer_hi, "page_lo": frame.page_lo,
               "page_hi": frame.page_hi}
        if frame.checksum is not None:
            # Omitted (not null) when absent so pre-checksum receivers
            # never see an unknown key with a surprising value.
            hdr["checksum"] = frame.checksum
        return (hdr, frame.k_bytes, frame.v_bytes)
    if isinstance(frame, StreamFirstToken):
        return ({"op": "kv_first", "stream_id": frame.stream_id,
                 "first_token": frame.first_token}, None, None)
    if isinstance(frame, StreamFin):
        return ({"op": "kv_fin", "stream_id": frame.stream_id,
                 "n_chunks": frame.n_chunks, "aborted": frame.aborted,
                 "error": frame.error}, None, None)
    raise ValueError(f"unknown frame {frame!r}")


def frame_from_wire(obj: dict, k: Optional[bytes],
                    v: Optional[bytes]) -> Frame:
    op = obj.get("op")
    if op == "kv_meta":
        return StreamMeta(stream_id=obj["stream_id"],
                          prompt=list(obj["prompt"]),
                          n_pages=int(obj["n_pages"]),
                          k_page_shape=tuple(obj["k_page_shape"]),
                          v_page_shape=tuple(obj["v_page_shape"]),
                          dtype=obj["dtype"], layers=int(obj["layers"]),
                          page_size=int(obj["page_size"]))
    if op == "kv_chunk":
        cs = obj.get("checksum")
        return KVChunk(stream_id=obj["stream_id"], seq=int(obj["seq"]),
                       layer_lo=int(obj["layer_lo"]),
                       layer_hi=int(obj["layer_hi"]),
                       page_lo=int(obj["page_lo"]),
                       page_hi=int(obj["page_hi"]),
                       k_bytes=k or b"", v_bytes=v or b"",
                       checksum=int(cs) if cs is not None else None)
    if op == "kv_first":
        return StreamFirstToken(obj["stream_id"], int(obj["first_token"]))
    if op == "kv_fin":
        return StreamFin(obj["stream_id"], n_chunks=int(obj["n_chunks"]),
                         aborted=bool(obj.get("aborted")),
                         error=obj.get("error") or "")
    raise StreamError(f"unknown kv frame op {op!r}")


class TCPTransport(Transport):
    """Client half of the TCP chunk stream: one connection per stream to
    the accepting server (the decode server's ``kv_stream`` op, or the
    standalone contract-test listener). ``peer`` is ``host:port``. The
    connection opens lazily on the first frame and closes after FIN."""

    name = "tcp"

    def __init__(self, token: Optional[str] = None,
                 connect_timeout: float = 5.0, io_timeout: float = 60.0):
        super().__init__()
        self.token = token
        self.connect_timeout = connect_timeout
        self.io_timeout = io_timeout
        self._lock = named_lock("kvtransfer.tcp")
        self._conns: Dict[str, socket.socket] = {}  # guarded_by[kvtransfer.tcp]

    def send_one(self, peer: str, frame: Frame) -> None:
        from rbg_tpu.engine.protocol import send_msg

        sid = getattr(frame, "stream_id", "")
        with self._lock:
            s = self._conns.get(sid)
        if s is None:
            host, port = peer.rsplit(":", 1)
            s = socket.create_connection((host, int(port)),
                                         timeout=self.connect_timeout)
            s.settimeout(self.io_timeout)
            hello = {"op": "kv_stream", "stream_id": sid}
            if self.token:
                hello["token"] = self.token
            send_msg(s, hello)
            with self._lock:
                self._conns[sid] = s
        hdr, kb, vb = frame_to_wire(frame)
        try:
            send_msg(s, hdr, kb, vb)
        except OSError as e:
            self._close(sid)
            raise StreamError(f"kv stream {sid} to {peer} broke: {e}") from e
        if isinstance(frame, StreamFin):
            self._drain_ack(sid, s)

    def _drain_ack(self, sid: str, s: socket.socket) -> None:
        from rbg_tpu.engine.protocol import recv_msg
        try:
            recv_msg(s)  # {"ok": true} / {"error": ...} — best effort
        except (OSError, ValueError):
            pass
        finally:
            self._close(sid)

    def _close(self, sid: str) -> None:
        with self._lock:
            s = self._conns.pop(sid, None)
        if s is not None:
            try:
                s.close()
            except OSError:
                pass

    def recv_chunks(self, stream_id: str,
                    timeout: float = 30.0) -> Iterator[Frame]:
        raise NotImplementedError(
            "TCP receive is socket-driven: the accepting server feeds a "
            "StreamRegistry from its kv_stream handler")
