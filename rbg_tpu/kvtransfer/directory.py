"""Cluster-wide prefix directory — which replica holds which KV prefix.

The router's prefix affinity used to be a last-serving-backend LRU: it
could only re-find a prefix on the ONE replica that most recently served
it. Mooncake's KVCache-centric design keeps a cluster-wide index instead:
any replica that published a page-aligned prefix (to its radix cache and
the shared pool) registers it here, and the router can route a request to
ANY holder.

* ``PrefixDirectory``  — the authoritative in-memory map: page-aligned
  prefix key (``chunks.prefix_keys`` hash chain — stable across
  processes) → {backend addr → entry}. Entries carry a ``slice_id`` tag
  so the disruption controller can invalidate a whole slice on
  preemption, and a TTL backstop against anything the explicit
  invalidation paths miss.
* ``DirectoryClient``  — wire client for the directory ops the kv-pool
  server hosts (``dir_register`` / ``dir_lookup`` / ``dir_invalidate`` /
  ``dir_stats``): the pool is already the cluster's shared KV service, so
  the index lives next to the data.

Lifecycle contract (the staleness satellite): entries are registered by
the prefill publish path, and invalidated on (a) pool/radix eviction of
the prefix, (b) backend drain (SIGTERM), (c) slice preemption or
maintenance (DisruptionController), (d) TTL expiry. A lookup must never
return an evicted prefix — the ``directory_consistent`` stress invariant.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

from rbg_tpu.kvtransfer.chunks import prefix_keys
from rbg_tpu.obs import names as obs_names
from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.utils.locktrace import named_lock
from rbg_tpu.utils.racetrace import guard as _race_guard


class _Entry:
    __slots__ = ("backend", "slice_id", "t_registered", "tier", "hits")

    def __init__(self, backend: str, slice_id: str, tier: str = "device"):
        self.backend = backend
        self.slice_id = slice_id
        self.t_registered = time.monotonic()
        # Cache tier the holder keeps this prefix in: "device" (radix /
        # HBM pool — a hit is ~free) or "host" (spill tier — a hit costs
        # the promote fetch). The router's tier-fetch-cost scoring reads
        # it; re-registration refreshes it (promotion flips host→device).
        self.tier = tier
        # Lookup hotness: times this entry fronted a deepest-key lookup.
        # The router replicates hot single-holder prefixes off it.
        self.hits = 0


@_race_guard
class PrefixDirectory:
    def __init__(self, page_size: Optional[int] = None,
                 ttl_s: float = 600.0, max_keys: int = 65536):
        self.page_size = page_size
        self.ttl_s = ttl_s
        self.max_keys = max_keys
        self._lock = named_lock("kvtransfer.directory")
        # key → {backend: _Entry}
        self._m: Dict[str, Dict[str, _Entry]] = {}  # guarded_by[kvtransfer.directory]
        # guarded_by[kvtransfer.directory]
        self.metrics = {"registers": 0, "lookups": 0, "hits": 0,
                        "invalidated": 0}

    # -- write paths --

    def register_keys(self, keys: List[str], backend: str,
                      slice_id: str = "", tier: str = "device") -> int:
        """Register a hash-chain of page keys for ``backend``. Returns the
        number of keys registered. Keys are refreshed, not duplicated;
        re-registration updates the tier tag (spill demotes to "host",
        promotion restores "device")."""
        if not keys or not backend:
            return 0
        now = time.monotonic()
        with self._lock:
            for key in keys:
                holders = self._m.get(key)
                if holders is None:
                    holders = self._m[key] = {}
                e = holders.get(backend)
                if e is None:
                    holders[backend] = _Entry(backend, slice_id, tier=tier)
                else:
                    e.t_registered = now
                    e.slice_id = slice_id or e.slice_id
                    e.tier = tier
            self.metrics["registers"] += 1
            self._cap_locked()
            n = len(self._m)
        REGISTRY.set_gauge(obs_names.KVT_DIR_ENTRIES, float(n))
        return len(keys)

    def register(self, tokens: List[int], backend: str,
                 slice_id: str = "", tier: str = "device") -> int:
        if self.page_size is None:
            raise ValueError("directory has no page_size; use register_keys")
        return self.register_keys(prefix_keys(tokens, self.page_size),
                                  backend, slice_id, tier=tier)

    def _invalidate_where(self, pred, reason: str) -> int:
        """Drop entries matching ``pred(key, entry)``; empty keys die."""
        dropped = 0
        with self._lock:
            for key in list(self._m):
                holders = self._m[key]
                for b in [b for b, e in holders.items() if pred(key, e)]:
                    del holders[b]
                    dropped += 1
                if not holders:
                    del self._m[key]
            self.metrics["invalidated"] += dropped
            n = len(self._m)
        if dropped:
            REGISTRY.inc(obs_names.KVT_DIR_INVALIDATIONS_TOTAL,
                         float(dropped), reason=reason)
            REGISTRY.set_gauge(obs_names.KVT_DIR_ENTRIES, float(n))
        return dropped

    def invalidate_backend(self, backend: str, reason: str = "drain") -> int:
        return self._invalidate_where(
            lambda _k, e: e.backend == backend, reason)

    def invalidate_slice(self, slice_id: str,
                         reason: str = "preemption") -> int:
        if not slice_id:
            return 0
        return self._invalidate_where(
            lambda _k, e: e.slice_id == slice_id, reason)

    def invalidate_keys(self, keys: List[str],
                        reason: str = "eviction",
                        backend: str = "") -> int:
        """Drop entries for these keys — scoped to ``backend`` when
        given. Scoping matters once host tiers are per-replica: replica
        A's byte-budget eviction of a shared (content-hashed) prefix
        key must not wipe replica B's still-valid claim for the same
        key. Empty backend keeps the key-wide semantics the single
        shared cluster pool relies on (the pool IS its only holder)."""
        ks = set(keys)
        if backend:
            return self._invalidate_where(
                lambda k, e: k in ks and e.backend == backend, reason)
        return self._invalidate_where(lambda k, _e: k in ks, reason)

    def _cap_locked(self) -> None:
        """Bound the index: evict oldest-registered keys past max_keys
        (caller holds the lock)."""
        over = len(self._m) - self.max_keys
        if over <= 0:
            return
        oldest = sorted(
            self._m,
            key=lambda k: max(e.t_registered
                              for e in self._m[k].values()))[:over]
        for k in oldest:
            del self._m[k]
        self.metrics["invalidated"] += over

    # -- read path --

    def lookup_entries(self, keys: List[str]) -> Tuple[int, List[dict]]:
        """Longest registered prefix of the key chain, with per-holder
        detail. Returns (matched_pages, [{backend, tier, hotness}] of the
        deepest matched key). TTL-expired entries are dropped on the way;
        each hit bumps the deepest entries' hotness (the replication
        signal)."""
        cutoff = time.monotonic() - self.ttl_s
        with self._lock:
            self.metrics["lookups"] += 1
            matched, deepest = 0, None
            for key in keys:
                hs = self._m.get(key)
                if hs:
                    for b in [b for b, e in hs.items()
                              if e.t_registered < cutoff]:
                        del hs[b]
                    if not hs:
                        del self._m[key]
                        hs = None
                if not hs:
                    break
                matched += 1
                deepest = hs
            detail = []
            if deepest is not None:
                for e in deepest.values():
                    e.hits += 1
                    detail.append({"backend": e.backend, "tier": e.tier,
                                   "hotness": e.hits})
            if matched:
                self.metrics["hits"] += 1
        REGISTRY.inc(obs_names.KVT_DIR_LOOKUPS_TOTAL,
                     result="hit" if matched else "miss")
        return matched, detail

    def lookup_keys(self, keys: List[str]) -> Tuple[int, List[str]]:
        """Longest registered prefix of the key chain. Returns
        (matched_pages, holders-of-the-deepest-matched-key)."""
        matched, detail = self.lookup_entries(keys)
        return matched, [d["backend"] for d in detail]

    def lookup(self, tokens: List[int]) -> Tuple[int, List[str]]:
        """Longest registered page-aligned prefix of ``tokens`` →
        (matched_tokens, holder backends)."""
        if self.page_size is None:
            raise ValueError("directory has no page_size; use lookup_keys")
        pages, holders = self.lookup_keys(
            prefix_keys(tokens, self.page_size))
        return pages * self.page_size, holders

    def lookup_detail(self, tokens: List[int]) -> Tuple[int, List[dict]]:
        """Longest registered page-aligned prefix of ``tokens`` →
        (matched_tokens, [{backend, tier, hotness}]) — the router's
        tier-fetch-cost scoring input."""
        if self.page_size is None:
            raise ValueError("directory has no page_size; use lookup_entries")
        pages, detail = self.lookup_entries(
            prefix_keys(tokens, self.page_size))
        return pages * self.page_size, detail

    def stats(self) -> dict:
        with self._lock:
            return {**self.metrics, "keys": len(self._m)}


class DirectoryClient:
    """Wire client for the directory ops hosted on the kv-pool server.
    Failures degrade (return misses / 0) — the directory is an
    optimization, never a request dependency. A failed call opens a
    circuit-breaker whose window GROWS with consecutive failures
    (``ExponentialBackoff``, decorrelated jitter): a flapping pool host
    is neither hammered at a fixed half-open cadence (N routers with the
    same 5 s window would reconnect in lockstep) nor allowed to
    blackhole affinity for a long fixed wall-clock window after one
    blip. A successful call snaps the window back to the base."""

    def __init__(self, addr: str, timeout: float = 2.0,
                 token: Optional[str] = None,
                 page_size: Optional[int] = None,
                 backoff_s: float = 0.5, backoff_max_s: float = 30.0,
                 chaos=None):
        import os
        from rbg_tpu.runtime.queue import ExponentialBackoff
        self.addr = addr
        self.timeout = timeout
        self.page_size = page_size
        self.backoff_s = backoff_s
        # Fault-injection hook (chaos.inject.directory_fault): called
        # inside the request try-block so an injected OSError rides the
        # REAL failure path (breaker, degraded gauge). None in production.
        self._chaos = chaos
        self._backoff = ExponentialBackoff(base=backoff_s,
                                           max_delay=backoff_max_s,
                                           jitter=True)
        self.token = (token if token is not None
                      else os.environ.get("RBG_DATA_TOKEN") or None)
        self._lock = named_lock("kvtransfer.dirclient")
        self._down_until = 0.0   # guarded_by[kvtransfer.dirclient]
        # True while ONE caller owns the half-open probe (see _call).
        self._probing = False    # guarded_by[kvtransfer.dirclient]

    def _call(self, obj: dict) -> Optional[dict]:
        from rbg_tpu.engine.protocol import request_once
        # Half-open single-flight: while the breaker window is open every
        # caller degrades instantly (local-affinity fast path). When the
        # window closes, exactly ONE caller becomes the probe; concurrent
        # callers keep degrading until the probe's verdict lands — N
        # routers recovering must not thundering-herd the pool host.
        probe = False
        with self._lock:
            if time.monotonic() < self._down_until:
                return None
            if self._down_until > 0.0:
                if self._probing:
                    return None
                self._probing = probe = True
        if self.token:
            obj = dict(obj, token=self.token)
        try:
            if self._chaos is not None:
                self._chaos()
            resp, _, _ = request_once(self.addr, obj, timeout=self.timeout)
        except (OSError, ValueError):
            with self._lock:
                delay = self._backoff.next_delay(self.addr)
                self._down_until = time.monotonic() + delay
                self._probing = False
            REGISTRY.inc(obs_names.KVT_DIR_BREAKER_OPEN_TOTAL)
            # Ladder rung engaged: the router serves on, affinity-only.
            REGISTRY.set_gauge(obs_names.DEGRADED_MODE, 1.0,
                               ladder="directory")
            return None
        if not isinstance(resp, dict) or resp.get("error"):
            if probe:
                with self._lock:
                    self._probing = False
            return None
        with self._lock:
            self._backoff.forget(self.addr)
            self._down_until = 0.0
            self._probing = False
        REGISTRY.set_gauge(obs_names.DEGRADED_MODE, 0.0,
                           ladder="directory")
        return resp

    def register_keys(self, keys: List[str], backend: str,
                      slice_id: str = "", tier: str = "device") -> int:
        resp = self._call({"op": "dir_register", "keys": list(keys),
                           "backend": backend, "slice_id": slice_id,
                           "tier": tier})
        return int(resp.get("registered", 0)) if resp else 0

    def register(self, tokens: List[int], backend: str,
                 slice_id: str = "", tier: str = "device") -> int:
        if self.page_size is None:
            return 0
        return self.register_keys(prefix_keys(tokens, self.page_size),
                                  backend, slice_id, tier=tier)

    def lookup_keys(self, keys: List[str]) -> Tuple[int, List[str]]:
        resp = self._call({"op": "dir_lookup", "keys": list(keys)})
        if not resp:
            return 0, []
        return int(resp.get("matched", 0)), list(resp.get("holders") or ())

    def lookup_detail(self, tokens: List[int]) -> Tuple[int, List[dict]]:
        """(matched_tokens, [{backend, tier, hotness}]) — like
        ``PrefixDirectory.lookup_detail`` but over the wire; the server
        computes the key chain with ITS page size when this client holds
        none. Degrades to (0, []) like every directory op."""
        if self.page_size is not None:
            obj = {"op": "dir_lookup", "detail": True,
                   "keys": prefix_keys(tokens, self.page_size)}
            resp = self._call(obj)
            if not resp:
                return 0, []
            return (int(resp.get("matched", 0)) * self.page_size,
                    list(resp.get("detail") or ()))
        resp = self._call({"op": "dir_lookup", "detail": True,
                           "prompt": list(tokens)})
        if not resp:
            return 0, []
        return (int(resp.get("matched_tokens", 0)),
                list(resp.get("detail") or ()))

    def lookup(self, tokens: List[int]) -> Tuple[int, List[str]]:
        """Longest registered prefix of ``tokens``. Without a local
        page_size the prompt goes to the server, which computes the key
        chain with ITS page size (the router has no engine config)."""
        if self.page_size is not None:
            pages, holders = self.lookup_keys(
                prefix_keys(tokens, self.page_size))
            return pages * self.page_size, holders
        resp = self._call({"op": "dir_lookup", "prompt": list(tokens)})
        if not resp:
            return 0, []
        return (int(resp.get("matched_tokens", 0)),
                list(resp.get("holders") or ()))

    def invalidate_backend(self, backend: str, reason: str = "drain") -> int:
        resp = self._call({"op": "dir_invalidate", "backend": backend,
                           "reason": reason})
        return int(resp.get("invalidated", 0)) if resp else 0

    def invalidate_slice(self, slice_id: str,
                         reason: str = "preemption") -> int:
        resp = self._call({"op": "dir_invalidate", "slice_id": slice_id,
                           "reason": reason})
        return int(resp.get("invalidated", 0)) if resp else 0

    def invalidate_keys(self, keys: List[str],
                        reason: str = "eviction",
                        backend: str = "") -> int:
        """Key-level invalidation (the KVPoolStore eviction path calls
        this on whatever directory handle it was built with — the wire
        client must honor the same contract as the in-proc directory).
        ``backend`` scopes the drop to one replica's claims."""
        obj = {"op": "dir_invalidate", "keys": list(keys),
               "reason": reason}
        if backend:
            obj["backend"] = backend
        resp = self._call(obj)
        return int(resp.get("invalidated", 0)) if resp else 0

    def stats(self) -> dict:
        resp = self._call({"op": "dir_stats"})
        return (resp or {}).get("directory", {})
