"""KVCache-centric transfer plane (Mooncake/NetKV analog, PAPERS.md).

Chunked, layer-overlapped prefill→decode KV streaming over an explicit
transport seam, plus the cluster-wide prefix directory the router's
cache-aware and transfer-cost-aware routing consults. See
docs/architecture.md "KV transfer plane".
"""

from rbg_tpu.kvtransfer.chunks import (ChunkAssembler, KVChunk,
                                       KVIntegrityError, StreamError,
                                       StreamFin, StreamFirstToken,
                                       StreamMeta, bundle_to_frames,
                                       payload_checksum, plan_chunks,
                                       prefix_keys, slab_to_chunks)
from rbg_tpu.kvtransfer.directory import DirectoryClient, PrefixDirectory
from rbg_tpu.kvtransfer.stream import KVStreamReceiver, StreamRegistry
from rbg_tpu.kvtransfer.transport import (FakeICITransport, InProcTransport,
                                          LinkStats, SlowLossyTransport,
                                          TCPTransport, Transport,
                                          frame_from_wire, frame_to_wire)

__all__ = [
    "ChunkAssembler", "KVChunk", "KVIntegrityError", "StreamError",
    "StreamFin", "StreamFirstToken", "StreamMeta", "bundle_to_frames",
    "payload_checksum", "plan_chunks", "prefix_keys", "slab_to_chunks",
    "DirectoryClient", "PrefixDirectory",
    "KVStreamReceiver", "StreamRegistry",
    "FakeICITransport", "InProcTransport", "LinkStats",
    "SlowLossyTransport", "TCPTransport", "Transport",
    "frame_from_wire", "frame_to_wire",
]
