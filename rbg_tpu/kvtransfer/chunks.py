"""KV chunk model for the transfer plane.

A prefill→decode KV handoff is a STREAM of frames instead of one
monolithic ``KVBundle`` (Mooncake's KVCache-centric transfer, PAPERS.md):

* ``StreamMeta``  — opens the stream: prompt, page geometry, dtypes.
  Arrives first; the receiver allocates its host staging buffers from it.
* ``KVChunk``     — one page-aligned, layer-ranged slab of K+V payload:
  ``[layer_lo:layer_hi) x [page_lo:page_hi)``. Chunks are published in
  layer order within a page group, page groups in prompt order — AS the
  prefill computes them — but the receiver tolerates reordering and
  duplicate delivery (a lossy link's retransmit must not corrupt KV).
* ``StreamFirstToken`` — the prefill-sampled first token, sent the moment
  prefill compute ends. Decode admission needs (full coverage AND the
  first token); everything after this frame is bookkeeping.
* ``StreamFin``   — closes the stream: chunk count for truncation
  detection. Admission deliberately does NOT wait for it — that is the
  overlap the plane exists to create.

Everything here is numpy/stdlib only (no jax): the wire processes import
it before an engine exists.
"""

from __future__ import annotations

import dataclasses
import hashlib
import zlib
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

from rbg_tpu.api.errors import (CODE_KV_INTEGRITY,  # dependency-free catalog
                                CODE_KV_STREAM)
from rbg_tpu.obs import names as obs_names
from rbg_tpu.obs.metrics import REGISTRY


@dataclasses.dataclass
class StreamMeta:
    stream_id: str
    prompt: List[int]
    n_pages: int
    # Per-page payload shapes EXCLUDING the layer and page axes:
    # k page slab is [L, n_pages, *k_page_shape] (e.g. (page, KV, hd)).
    k_page_shape: Tuple[int, ...]
    v_page_shape: Tuple[int, ...]
    dtype: str
    layers: int
    page_size: int

    def k_shape(self) -> Tuple[int, ...]:
        return (self.layers, self.n_pages) + tuple(self.k_page_shape)

    def v_shape(self) -> Tuple[int, ...]:
        return (self.layers, self.n_pages) + tuple(self.v_page_shape)

    def nbytes(self) -> int:
        item = np.dtype(self.dtype).itemsize
        per_page = (int(np.prod(self.k_page_shape))
                    + int(np.prod(self.v_page_shape)))
        return self.layers * self.n_pages * per_page * item


@dataclasses.dataclass
class KVChunk:
    stream_id: str
    seq: int
    layer_lo: int
    layer_hi: int
    page_lo: int
    page_hi: int
    k_bytes: bytes
    v_bytes: bytes
    # End-to-end payload checksum minted by the PRODUCER (slab_to_chunks)
    # and verified at decode commit (ChunkAssembler.feed) — None only for
    # frames from a pre-checksum sender (back-compat: verify when present).
    checksum: Optional[int] = None

    @property
    def nbytes(self) -> int:
        return len(self.k_bytes) + len(self.v_bytes)

    def key(self) -> Tuple[int, int, int, int]:
        return (self.layer_lo, self.layer_hi, self.page_lo, self.page_hi)


@dataclasses.dataclass
class StreamFirstToken:
    stream_id: str
    first_token: int


@dataclasses.dataclass
class StreamFin:
    stream_id: str
    n_chunks: int
    aborted: bool = False
    error: str = ""


# Any frame kind riding a transport.
Frame = object


class StreamError(RuntimeError):
    """Structured stream failure (truncated, aborted, shape mismatch) —
    the receiver surfaces it instead of wedging on a never-ready row.
    ``wire_code`` rides error frames so the ROUTER recognizes the class
    and recovers by re-prefilling in bundle mode instead of surfacing a
    raw error to the client."""

    wire_code = CODE_KV_STREAM


class KVIntegrityError(StreamError):
    """A KV payload failed its end-to-end checksum — bytes corrupted
    between the producer's compute and the consumer's commit. A subclass
    of ``StreamError`` so every existing recovery path (receiver error
    surface, router bundle-fallback replay) engages unchanged; the
    distinct ``wire_code`` keeps "bytes lied" separable from "link
    flaked" at the edge and in accounting."""

    wire_code = CODE_KV_INTEGRITY


def payload_checksum(k_bytes: bytes, v_bytes: bytes) -> int:
    """CRC32 over the concatenated K+V payload — cheap enough to run on
    every chunk/page, strong enough to catch the bit flips and torn
    writes partitioned links actually produce (not an adversarial MAC)."""
    return zlib.crc32(v_bytes, zlib.crc32(k_bytes))


def plan_chunks(meta: StreamMeta, page_lo: int, page_hi: int,
                layer_split: int) -> List[Tuple[int, int, int, int]]:
    """(layer_lo, layer_hi, page_lo, page_hi) plan for one page group,
    layer-ordered: the receiver sees low layers of a page group first, so
    a layer-pipelining decoder could start before the group completes.
    ``layer_split`` caps layers per chunk (1 = one chunk per layer)."""
    out = []
    step = max(1, int(layer_split))
    for lo in range(0, meta.layers, step):
        out.append((lo, min(lo + step, meta.layers), page_lo, page_hi))
    return out


def slab_to_chunks(meta: StreamMeta, k_slab: np.ndarray, v_slab: np.ndarray,
                   page_lo: int, seq0: int,
                   layer_split: int) -> List[KVChunk]:
    """Cut one freshly-computed page group (``k_slab``/``v_slab`` are
    ``[L, pages, ...]`` covering pages ``[page_lo, page_lo+pages)``) into
    layer-ordered chunks ready to send."""
    chunks = []
    pages = k_slab.shape[1]
    for i, (llo, lhi, plo, phi) in enumerate(
            plan_chunks(meta, page_lo, page_lo + pages, layer_split)):
        kb = np.ascontiguousarray(
            k_slab[llo:lhi, plo - page_lo:phi - page_lo]).tobytes()
        vb = np.ascontiguousarray(
            v_slab[llo:lhi, plo - page_lo:phi - page_lo]).tobytes()
        chunks.append(KVChunk(
            stream_id=meta.stream_id, seq=seq0 + i,
            layer_lo=llo, layer_hi=lhi, page_lo=plo, page_hi=phi,
            k_bytes=kb, v_bytes=vb,
            checksum=payload_checksum(kb, vb),
        ))
    return chunks


def bundle_to_frames(meta: StreamMeta, k_data: np.ndarray,
                     v_data: np.ndarray, first_token: int,
                     layer_split: int = 0) -> List[Frame]:
    """Whole-bundle → frame list (meta, chunks, first token, fin) — the
    replay/retransmit source and the contract-test generator.
    ``layer_split`` 0 means one chunk for all layers per page group."""
    split = layer_split or meta.layers
    chunks: List[KVChunk] = []
    for plo in range(0, meta.n_pages):
        chunks.extend(slab_to_chunks(
            meta, k_data[:, plo:plo + 1], v_data[:, plo:plo + 1],
            plo, len(chunks), split))
    return ([meta] + list(chunks)
            + [StreamFirstToken(meta.stream_id, first_token),
               StreamFin(meta.stream_id, n_chunks=len(chunks))])


class ChunkAssembler:
    """Host-side reassembly of a chunk stream into full ``[L, n_pages,
    ...]`` K/V arrays, tolerant of reordering and duplicate delivery.

    Not thread-safe by itself — the owning receiver serializes feeds.
    ``coverage_complete()`` is the admission predicate: every (layer,
    page) cell seen at least once. All of this is host memory; the device
    page-table commit belongs to the engine loop thread.
    """

    def __init__(self, meta: StreamMeta):
        self.meta = meta
        dt = np.dtype(meta.dtype)
        self.k = np.zeros(meta.k_shape(), dt)
        self.v = np.zeros(meta.v_shape(), dt)
        # Per-cell arrival map [L, n_pages] — duplicates simply rewrite.
        self._have = np.zeros((meta.layers, meta.n_pages), bool)
        self.first_token: Optional[int] = None
        self.fin: Optional[StreamFin] = None
        self.chunks_seen = 0
        self.dup_chunks = 0
        self.reordered_chunks = 0
        self.bytes_seen = 0
        self._max_seq = -1
        # (layer_lo, layer_hi, page_lo, page_hi) cells already applied —
        # the "new for the page table" delta the committer drains.
        self._uncommitted: List[Tuple[int, int, int, int]] = []

    def feed(self, frame: Frame) -> None:
        if isinstance(frame, StreamMeta):
            return  # receiver constructed us from it
        if isinstance(frame, StreamFirstToken):
            self.first_token = int(frame.first_token)
            return
        if isinstance(frame, StreamFin):
            self.fin = frame
            return
        ch: KVChunk = frame
        m = self.meta
        if not (0 <= ch.layer_lo < ch.layer_hi <= m.layers
                and 0 <= ch.page_lo < ch.page_hi <= m.n_pages):
            raise StreamError(
                f"chunk range out of bounds: layers [{ch.layer_lo},"
                f"{ch.layer_hi}) pages [{ch.page_lo},{ch.page_hi}) for "
                f"meta L={m.layers} n_pages={m.n_pages}")
        dt = np.dtype(m.dtype)
        kshape = (ch.layer_hi - ch.layer_lo, ch.page_hi - ch.page_lo) \
            + tuple(m.k_page_shape)
        vshape = (ch.layer_hi - ch.layer_lo, ch.page_hi - ch.page_lo) \
            + tuple(m.v_page_shape)
        if (len(ch.k_bytes) != int(np.prod(kshape)) * dt.itemsize
                or len(ch.v_bytes) != int(np.prod(vshape)) * dt.itemsize):
            raise StreamError(
                f"chunk payload size mismatch for range layers "
                f"[{ch.layer_lo},{ch.layer_hi}) pages "
                f"[{ch.page_lo},{ch.page_hi})")
        if self._have[ch.layer_lo:ch.layer_hi,
                      ch.page_lo:ch.page_hi].all():
            # Retransmit of cells already committed — tolerated, but a
            # degrading link retransmits before it truncates: count it.
            self.dup_chunks += 1
            REGISTRY.inc(obs_names.KVT_CHUNKS_DUPLICATE_TOTAL)
            return
        if ch.seq < self._max_seq:
            # Arrived after a higher seq (duplicates excluded above):
            # the link is reordering — visible before it corrupts.
            self.reordered_chunks += 1
            REGISTRY.inc(obs_names.KVT_CHUNKS_REORDERED_TOTAL)
        self._max_seq = max(self._max_seq, ch.seq)
        if ch.checksum is not None \
                and payload_checksum(ch.k_bytes, ch.v_bytes) != ch.checksum:
            # Verified BEFORE the bytes touch the assembly buffers: a
            # corrupt payload never becomes committable KV. The error
            # rides the receiver's structured-failure surface, so the
            # router replays the whole stream token-exact (bundle
            # fallback) — never a wedge, never silent corruption.
            REGISTRY.inc(obs_names.KVT_INTEGRITY_FAILURES_TOTAL,
                         surface="chunk")
            raise KVIntegrityError(
                f"chunk seq={ch.seq} layers [{ch.layer_lo},{ch.layer_hi}) "
                f"pages [{ch.page_lo},{ch.page_hi}) failed its payload "
                f"checksum — corrupted in flight")
        self.k[ch.layer_lo:ch.layer_hi, ch.page_lo:ch.page_hi] = \
            np.frombuffer(ch.k_bytes, dt).reshape(kshape)
        self.v[ch.layer_lo:ch.layer_hi, ch.page_lo:ch.page_hi] = \
            np.frombuffer(ch.v_bytes, dt).reshape(vshape)
        self._have[ch.layer_lo:ch.layer_hi, ch.page_lo:ch.page_hi] = True
        self.chunks_seen += 1
        self.bytes_seen += ch.nbytes
        self._uncommitted.append(ch.key())

    def coverage_complete(self) -> bool:
        return bool(self._have.all())

    def layer_coverage(self) -> int:
        """Number of LEADING layers with every page cell covered — the
        layer-sliced admission watermark. Chunks are published layer-
        ordered within a page group (``plan_chunks``), so on an in-order
        link this grows monotonically front-to-back; on a lossy/reordered
        link it is simply the honest prefix."""
        full = self._have.all(axis=1)           # [L]
        return int(np.cumprod(full).sum())

    def ready(self) -> bool:
        """Admission predicate: full coverage + the prefill-sampled first
        token. Deliberately independent of FIN."""
        return self.coverage_complete() and self.first_token is not None

    def ready_layers(self, min_layers: int) -> bool:
        """Layer-sliced admission predicate: the first ``min_layers``
        layers fully covered + the first token — the Mooncake-style
        layer-ordered arrival finally pays off (decode's layer 0 can
        start while layer L-1 is still on the wire)."""
        return (self.first_token is not None
                and self.layer_coverage() >= min_layers)

    def drain_uncommitted(self) -> List[Tuple[int, int, int, int]]:
        out, self._uncommitted = self._uncommitted, []
        return out

    def check_closed(self) -> None:
        """After FIN: raise a structured error on truncation/abort instead
        of letting a half-stream read as a wedge."""
        if self.fin is None:
            return
        if self.fin.aborted:
            raise StreamError(self.fin.error or "stream aborted by sender")
        if not self.coverage_complete():
            missing = int((~self._have).sum())
            raise StreamError(
                f"stream closed with {missing} uncovered (layer, page) "
                f"cells — truncated transfer")


# ---- cluster prefix keys -----------------------------------------------


def prefix_keys(tokens: List[int], page_size: int) -> List[str]:
    """Stable page-aligned prefix keys: a hash CHAIN, one key per full
    page, key_i covering tokens[0:(i+1)*page_size]. sha1-based so every
    process (any PYTHONHASHSEED) derives identical keys — the cluster
    directory's join key."""
    out = []
    h = hashlib.sha1()
    n = (len(tokens) // page_size) * page_size
    for i in range(0, n, page_size):
        h.update(np.asarray(tokens[i:i + page_size], np.int64).tobytes())
        out.append(h.hexdigest()[:20])
        h = hashlib.sha1(out[-1].encode())
    return out
