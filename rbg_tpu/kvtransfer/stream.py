"""Stream receiver + registry: the decode-side half of the transfer plane.

``KVStreamReceiver`` wraps a ``ChunkAssembler`` with the thread contract
the decode service needs:

* frames are FED on transport/connection threads (host-memory staging
  only — never the engine);
* ``wait_ready`` blocks a server handler until admission coverage (all
  (layer, page) cells + first token) or a structured ``StreamError``;
* the ENGINE LOOP thread drains committed-chunk deltas and performs the
  device page-table writes (single-writer engine contract) — copy outside
  the critical section, commit under it.

``StreamRegistry`` resolves arrival races: the KV stream connection and
the ``decode_stream`` request for the same ``stream_id`` may land in
either order.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

from rbg_tpu.kvtransfer.chunks import (ChunkAssembler, Frame, KVChunk,
                                       StreamError, StreamFin, StreamMeta)
from rbg_tpu.obs import names as obs_names
from rbg_tpu.obs.metrics import REGISTRY
from rbg_tpu.utils.locktrace import named_condition


class KVStreamReceiver:
    def __init__(self, stream_id: str):
        self.stream_id = stream_id
        self._cond = named_condition("kvtransfer.receiver")
        self.assembler: Optional[ChunkAssembler] = None  # guarded_by[kvtransfer.receiver]
        self._pre_meta: List[Frame] = []   # guarded_by[kvtransfer.receiver]
        self._error: Optional[str] = None  # guarded_by[kvtransfer.receiver]
        self.t_open = time.monotonic()
        self.t_ready: Optional[float] = None   # coverage+first_token time
        self.t_fin: Optional[float] = None     # stream-close time
        self.t_first_step: Optional[float] = None  # stamped by the decoder
        # Layer-sliced admission bookkeeping: the decode worker stamps
        # these when it admits at layer-k coverage (before full ready).
        self.t_layer_ready: Optional[float] = None  # min_layers reached
        self.layers_at_admit: Optional[int] = None  # coverage at admit
        self.total_layers: Optional[int] = None
        self._min_layers: int = 0          # guarded_by[kvtransfer.receiver]

    # -- producer side (transport / connection threads) --

    def feed(self, frame: Frame) -> None:
        with self._cond:
            try:
                if isinstance(frame, StreamMeta):
                    if self.assembler is None:
                        self.assembler = ChunkAssembler(frame)
                        for f in self._pre_meta:
                            self.assembler.feed(f)
                        self._pre_meta.clear()
                elif self.assembler is None:
                    # Reordered link delivered data before META — hold it.
                    self._pre_meta.append(frame)
                else:
                    self.assembler.feed(frame)
                    if isinstance(frame, KVChunk):
                        REGISTRY.inc(obs_names.KVT_CHUNKS_TOTAL,
                                     direction="recv")
            except StreamError as e:
                self._error = str(e)
            a = self.assembler
            if a is not None:
                if (self.t_layer_ready is None and self._min_layers > 0
                        and a.ready_layers(self._min_layers)):
                    self.t_layer_ready = time.monotonic()
                if self.t_ready is None and a.ready():
                    self.t_ready = time.monotonic()
                    if self.t_layer_ready is not None:
                        # The overlap the layer-sliced admission created:
                        # how long before FULL coverage the decode side
                        # could already start.
                        REGISTRY.observe(
                            obs_names.KVT_LAYER_ADMIT_LEAD_SECONDS,
                            max(0.0, self.t_ready - self.t_layer_ready))
                if a.fin is not None and self.t_fin is None:
                    self.t_fin = time.monotonic()
                    # An abort AFTER coverage is complete is harmless —
                    # the data all arrived; only an incomplete stream's
                    # abort/truncation is a failure.
                    if self._error is None and not a.ready():
                        if a.fin.aborted:
                            self._error = a.fin.error or "stream aborted"
                        else:
                            try:
                                a.check_closed()
                            except StreamError as e:
                                self._error = str(e)
                    if self.t_ready is not None and self._error is None:
                        REGISTRY.observe(
                            obs_names.KVT_ADMIT_LEAD_SECONDS,
                            max(0.0, self.t_fin - self.t_ready))
            self._cond.notify_all()

    def fail(self, msg: str) -> None:
        """Transport-level failure (connection died before FIN)."""
        with self._cond:
            if self._error is None:
                self._error = msg
            self._cond.notify_all()

    def pump(self, transport, timeout: float = 30.0) -> None:
        """Drive a transport's frame iterator into this receiver until FIN
        — the in-proc receiver-thread body."""
        try:
            for frame in transport.recv_chunks(self.stream_id,
                                               timeout=timeout):
                self.feed(frame)
        except StreamError as e:
            self.fail(str(e))

    # -- consumer side --

    def error(self) -> Optional[str]:
        with self._cond:
            return self._error

    def ready(self) -> bool:
        with self._cond:
            return (self._error is None and self.assembler is not None
                    and self.assembler.ready())

    def ready_layers(self, min_layers: int) -> bool:
        """Layer-sliced readiness: first ``min_layers`` layers fully
        covered + first token (also registers the watermark so feed()
        stamps ``t_layer_ready`` the moment it is crossed)."""
        with self._cond:
            if min_layers > self._min_layers:
                self._min_layers = min_layers
            a = self.assembler
            ok = (self._error is None and a is not None
                  and a.ready_layers(min_layers))
            if ok and self.t_layer_ready is None:
                self.t_layer_ready = time.monotonic()
            return ok

    def layer_coverage(self) -> int:
        with self._cond:
            a = self.assembler
            return 0 if a is None else a.layer_coverage()

    def wait_ready(self, timeout: float,
                   min_layers: int = 0) -> "ChunkAssembler":
        """Block until admission coverage or failure. With ``min_layers``
        > 0, returns as soon as the FIRST ``min_layers`` layers are fully
        covered (+ first token) — the layer-sliced admission entry; the
        caller must then verify per-layer watermarks before each dispatch
        and fall back to a full-coverage wait on a miss. Returns the
        assembler; raises StreamError on abort/truncation/timeout."""
        deadline = time.monotonic() + timeout
        with self._cond:
            if min_layers > self._min_layers:
                self._min_layers = min_layers
            while True:
                if self._error is not None:
                    raise StreamError(self._error)
                a = self.assembler
                if a is not None:
                    if min_layers > 0 and a.ready_layers(min_layers):
                        if self.t_layer_ready is None:
                            self.t_layer_ready = time.monotonic()
                        return a
                    if a.ready():
                        return a
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise StreamError(
                        f"stream {self.stream_id} not ready within "
                        f"{timeout}s (coverage "
                        f"{'n/a' if a is None else a.chunks_seen})")
                self._cond.wait(remaining)

    def drain_uncommitted(self) -> List[Tuple[int, int, int, int]]:
        """New (layer_lo, layer_hi, page_lo, page_hi) cells staged since
        the last drain — the engine-loop committer's work list."""
        with self._cond:
            if self.assembler is None:
                return []
            return self.assembler.drain_uncommitted()

    def admit_lead_s(self) -> Optional[float]:
        """Seconds between admission-readiness and stream close — the
        overlap the plane creates (None until both happened)."""
        if self.t_ready is None or self.t_fin is None:
            return None
        return self.t_fin - self.t_ready


class StreamRegistry:
    """stream_id → receiver, created by WHOEVER arrives first (the KV
    stream connection or the decode_stream request). Entries expire after
    ``ttl_s`` without consumption so an abandoned push cannot leak host
    staging buffers forever."""

    def __init__(self, ttl_s: float = 120.0):
        self.ttl_s = ttl_s
        self._cond = named_condition("kvtransfer.registry")
        self._streams: Dict[str, KVStreamReceiver] = {}  # guarded_by[kvtransfer.registry]

    def get_or_create(self, stream_id: str) -> KVStreamReceiver:
        with self._cond:
            self._gc_locked()
            r = self._streams.get(stream_id)
            if r is None:
                r = self._streams[stream_id] = KVStreamReceiver(stream_id)
                self._cond.notify_all()
            return r

    def pop(self, stream_id: str) -> None:
        with self._cond:
            self._streams.pop(stream_id, None)

    def active(self) -> List[str]:
        with self._cond:
            return list(self._streams)

    def _gc_locked(self) -> None:
        now = time.monotonic()
        for sid in [s for s, r in self._streams.items()
                    if now - r.t_open > self.ttl_s]:
            del self._streams[sid]
